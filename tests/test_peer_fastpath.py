"""Columnar peer fast path (ISSUE 3): the pooled per-peer send lanes,
depth-K pipelined forward RPCs, retry → circuit-open → fail-fast, and
the fused owner-side wire ingest.

Pinned here:
- forwarded responses are BYTE-identical to local wire serving and
  field-identical to the pure-Python OracleEngine;
- exact hit conservation under 16 concurrent callers spread over a
  3-daemon cluster (shared keys debit once per hit, ring-global);
- a peer dying mid-stream degrades to per-request error responses
  (bounded time, no stuck futures), opens the circuit after the
  configured consecutive failures (subsequent sends fail fast), and
  recovers through the half-open probe once the peer returns.
"""
import time

import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.oracle import Oracle
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import Algorithm, RateLimitRequest

pytest.importorskip("gubernator_tpu.ops._native",
                    reason="columnar peer lanes need the C++ codec")

DAY = 24 * 3_600_000
NOW0 = 1_760_000_000_000


@pytest.fixture(scope="module")
def mesh():
    from gubernator_tpu.parallel import make_mesh

    return make_mesh(n=1)


def serialize(reqs):
    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        m.name = r.name
        m.unique_key = r.unique_key
        m.hits = r.hits
        m.limit = r.limit
        m.duration = r.duration
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
        m.burst = r.burst
    return msg.SerializeToString()


def mk_wave(w: int, name="pfp"):
    reqs = []
    for i in range(30):
        reqs.append(RateLimitRequest(
            name=name, unique_key=f"t{i}", hits=1 + (i + w) % 3, limit=9,
            duration=DAY, algorithm=Algorithm.TOKEN_BUCKET))
    for i in range(10):
        reqs.append(RateLimitRequest(
            name=name, unique_key=f"l{i}", hits=2, limit=40,
            duration=DAY, algorithm=Algorithm.LEAKY_BUCKET, burst=12))
    for i in range(5):  # in-batch duplicates: segment semantics must
        # survive the forward/merge round trip
        reqs.append(RateLimitRequest(
            name=name, unique_key=f"t{i}", hits=2, limit=9,
            duration=DAY, algorithm=Algorithm.TOKEN_BUCKET))
    return reqs


class TestForwardedByteParity:
    """A worker-only daemon (ring omits itself) forwards EVERY request
    over the columnar lane; its response bytes must equal a solo
    instance serving the same stream locally, and both must match the
    oracle."""

    @pytest.fixture(scope="class")
    def pair(self):
        c = cluster_mod.start(2)
        owner, worker = c.daemon_at(0), c.daemon_at(1)
        owner.set_peers([owner.peer_info()])
        worker.set_peers([owner.peer_info()])
        yield c
        c.stop()

    def test_forwarded_bytes_equal_local_and_oracle(self, pair, mesh,
                                                    monkeypatch):
        worker = pair.instance_at(1)
        solo = V1Instance(Config(cache_size=1 << 12,
                                 sweep_interval_ms=0), mesh=mesh)
        try:
            oracle = Oracle()
            for w in range(3):
                # the peer wire stamps forwarded batches with the
                # OWNER's clock; pin it (in-process cluster — one
                # module) so parity is exact down to reset_time bytes
                monkeypatch.setattr(
                    "gubernator_tpu.instance.clock_ms",
                    lambda w=w: NOW0 + w)
                reqs = mk_wave(w)
                data = serialize(reqs)
                fwd = worker.get_rate_limits_wire(data, now_ms=NOW0 + w)
                loc = solo.get_rate_limits_wire(data, now_ms=NOW0 + w)
                assert fwd == loc, f"wave {w}: forwarded bytes differ " \
                    "from local wire serving"
                want = oracle.check_batch(reqs, NOW0 + w)
                got = pb.GetRateLimitsResp.FromString(fwd)
                assert len(got.responses) == len(reqs)
                for i, (g, e) in enumerate(zip(got.responses, want)):
                    assert g.error == "", (w, i, g.error)
                    assert (int(g.status), int(g.remaining),
                            int(g.limit), int(g.reset_time)) == \
                        (int(e.status), int(e.remaining),
                         int(e.limit), int(e.reset_time)), (w, i)
        finally:
            solo.close()


class TestConcurrentConservation:
    """16 concurrent callers spread over a 3-daemon cluster hammer a
    small shared key set: every hit must debit exactly once
    cluster-wide (ring ownership + the pooled forward lanes must not
    lose, duplicate, or misroute a request)."""

    def test_exact_conservation_16_callers(self):
        import threading

        c = cluster_mod.start(3)
        try:
            n_threads, reps, hits = 16, 12, 3
            keys = [f"c{i}" for i in range(4)]
            limit = 10 ** 6

            def one(hits_, key):
                return serialize([RateLimitRequest(
                    name="cons", unique_key=key, hits=hits_,
                    limit=limit, duration=DAY)])

            # warm every daemon's engine + forward lanes
            for d in range(3):
                for k in keys:
                    c.instance_at(d).get_rate_limits_wire(
                        one(0, k), now_ms=NOW0)
            errs = []

            def worker(t):
                inst = c.instance_at(t % 3)
                try:
                    for r in range(reps):
                        out = pb.GetRateLimitsResp.FromString(
                            inst.get_rate_limits_wire(
                                one(hits, keys[(t + r) % len(keys)]),
                                now_ms=NOW0 + 1 + r))
                        assert out.responses[0].error == ""
                except Exception as e:  # noqa: BLE001
                    errs.append(repr(e))

            ths = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
            for th in ths:
                th.start()
            for th in ths:
                th.join(timeout=120)
            assert not any(th.is_alive() for th in ths), "stuck caller"
            assert not errs, errs[:3]
            total = 0
            for k in keys:
                q = pb.GetRateLimitsResp.FromString(
                    c.instance_at(0).get_rate_limits_wire(
                        one(0, k), now_ms=NOW0 + 100))
                total += limit - int(q.responses[0].remaining)
            assert total == n_threads * reps * hits, \
                f"conservation broken: {total} != " \
                f"{n_threads * reps * hits}"
        finally:
            c.stop()


class TestPeerDeathCircuit:
    """Peer death mid-stream: bounded-time error responses (retry with
    backoff first), circuit-open after the threshold, fail-fast while
    open, half-open recovery when the peer returns."""

    @pytest.fixture()
    def fast_circuit(self):
        # degraded fallback + health gating OFF: this test pins the
        # raw error-row / fail-fast semantics underneath them (ISSUE 5
        # covers the degraded path in tests/test_resilience.py)
        return BehaviorConfig(batch_timeout_ms=200, batch_wait_ms=100,
                              peer_retry_limit=1,
                              peer_retry_backoff_ms=5,
                              peer_circuit_threshold=2,
                              peer_circuit_cooldown_ms=700,
                              peer_degraded_fallback=False,
                              peer_health_gate=False)

    def test_retry_circuit_failfast_recover(self, fast_circuit):
        c = cluster_mod.start(2, behaviors=fast_circuit)
        try:
            inst = c.instance_at(0)
            # keys owned by daemon 1 (they will be forwarded)
            owned1 = []
            for i in range(300):
                k = f"d{i}"
                if c.owner_daemon_of("pd_" + k) is c.daemon_at(1):
                    owned1.append(k)
                if len(owned1) >= 3:
                    break
            assert len(owned1) >= 3
            peer1 = next(p for p in inst.peers()
                         if not inst.is_self(p))

            def fire(key):
                t0 = time.monotonic()
                out = pb.GetRateLimitsResp.FromString(
                    inst.get_rate_limits_wire(serialize(
                        [RateLimitRequest(name="pd", unique_key=key,
                                          hits=1, limit=10,
                                          duration=DAY)]),
                        now_ms=NOW0))
                return out.responses[0], time.monotonic() - t0

            r, _ = fire(owned1[0])
            assert r.error == ""  # healthy forward first
            c.daemon_at(1).close()
            # dead peer: every forward degrades to an error response in
            # bounded time (connection-refused fails fast; retries add
            # only the short backoff), never a stuck future
            deadline = time.monotonic() + 30
            while not peer1.circuit_open():
                assert time.monotonic() < deadline, \
                    "circuit never opened"
                r, dt = fire(owned1[1])
                assert "while fetching rate limit from peer" in r.error
                assert dt < 10, f"forward took {dt:.1f}s"
            # fail-fast while open: no RPC, so the error returns in
            # well under a connection timeout
            r, dt = fire(owned1[2])
            assert "while fetching rate limit from peer" in r.error
            assert dt < 0.5, f"circuit-open forward took {dt:.3f}s"
            m = inst.metrics
            assert m.peer_circuit_open_counter.labels(
                peer_addr=peer1.info.grpc_address)._value.get() >= 1
            assert m.peer_retry_counter.labels(
                peer_addr=peer1.info.grpc_address)._value.get() >= 1
            # recovery: bring the peer back on the same address, wait
            # out the cooldown, and the half-open probe flush closes
            # the circuit
            c.restart(1)
            peer1b = next(p for p in c.instance_at(0).peers()
                          if not c.instance_at(0).is_self(p))
            deadline = time.monotonic() + 30
            while True:
                time.sleep(0.2)
                r, _ = fire(owned1[1])
                if r.error == "" and not peer1b.circuit_open():
                    break
                assert time.monotonic() < deadline, \
                    f"circuit never recovered (last error: {r.error!r})"
        finally:
            c.stop()

"""Daemon lifecycle tests: TLS, checkpoint/resume across restart,
discovery sources (reference: tls_test.go + cluster restart flows)."""
import json
import time

import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.client import Client
from gubernator_tpu.config import (
    BehaviorConfig,
    DaemonConfig,
    TLSSettings,
)
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.discovery import (
    DnsDiscovery,
    FileDiscovery,
    GossipDiscovery,
    StaticDiscovery,
)
from gubernator_tpu.netutil import free_port
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.types import PeerInfo, RateLimitRequest, Status


def req(name, key, **kw):
    d = dict(hits=1, limit=5, duration=60_000)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, **d)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(n=2)


def test_auto_tls_round_trip(mesh):
    """reference: tls_test.go › AutoTLS server + TLS client."""
    cfg = DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address="",
        cache_size=1 << 10,
        tls=TLSSettings(auto_tls=True))
    d = spawn_daemon(cfg, mesh=mesh)
    try:
        creds = d.tls.grpc_client_credentials()
        # the cert's SAN covers "localhost"/127.0.0.1
        with Client(f"localhost:{d.grpc_port}", tls_creds=creds) as c:
            r = c.check(req("tls_test", "k1", limit=3))
            assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 2)
    finally:
        d.close()


def test_tls_client_auth_required(mesh):
    """Client-auth mode: a client without a cert must be rejected."""
    import grpc

    cfg = DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address="",
        cache_size=1 << 10,
        tls=TLSSettings(auto_tls=True, client_auth="require-any"))
    d = spawn_daemon(cfg, mesh=mesh)
    try:
        good = d.tls.grpc_client_credentials()  # carries the daemon cert
        with Client(f"localhost:{d.grpc_port}", tls_creds=good,
                    timeout_s=10) as c:
            assert c.check(req("tls_auth", "k1")).error == ""
        bad = grpc.ssl_channel_credentials(
            root_certificates=d.tls.ca_pem)  # no client cert
        with pytest.raises(grpc.RpcError):
            with Client(f"localhost:{d.grpc_port}", tls_creds=bad,
                        timeout_s=5) as c:
                c.check(req("tls_auth", "k2"))
    finally:
        d.close()


def test_restart_with_snapshot_resumes_state(tmp_path, mesh):
    """Loader wiring: shutdown saves, restart loads — counters survive
    (store.go › Loader + cluster.go › Restart analog)."""
    cfgs = [DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address="",
        cache_size=1 << 10,
        snapshot_path=str(tmp_path / f"snap{i}.npz"),
        behaviors=BehaviorConfig(batch_timeout_ms=30))
        for i in range(2)]
    c = cluster_mod.start_with(cfgs, mesh=mesh)
    try:
        with Client(c.grpc_address(0)) as cl:
            for _ in range(3):
                r = cl.check(req("restart_test", "k1", limit=9))
            assert r.remaining == 6
        c.restart(0)
        c.restart(1)
        with Client(c.grpc_address(0)) as cl:
            r = cl.check(req("restart_test", "k1", hits=0, limit=9))
            assert r.remaining == 6, "state lost across restart"
    finally:
        c.stop()


def test_static_discovery():
    got = []
    StaticDiscovery(got.append, [PeerInfo(grpc_address="a:1"),
                                 PeerInfo(grpc_address="b:1")])
    assert len(got) == 1 and len(got[0]) == 2


def test_file_discovery(tmp_path):
    p = tmp_path / "peers.txt"
    p.write_text("# comment\n10.0.0.1:1051\n10.0.0.2:1051;10.0.0.2:1050@dc2\n")
    got = []
    fd = FileDiscovery(got.append, str(p), poll_interval_ms=20)
    try:
        assert len(got) == 1
        peers = got[0]
        assert peers[0].grpc_address == "10.0.0.1:1051"
        assert peers[1].datacenter == "dc2"
        # JSON format + change detection
        time.sleep(0.05)
        p.write_text(json.dumps(
            [{"grpc_address": "10.0.0.3:1051"}]))
        deadline = time.time() + 5
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert len(got) >= 2
        assert got[-1][0].grpc_address == "10.0.0.3:1051"
    finally:
        fd.close()


def test_dns_discovery():
    got = []
    dd = DnsDiscovery(got.append, "localhost", 1051, poll_interval_ms=60_000)
    try:
        assert got, "localhost must resolve"
        assert got[0][0].grpc_address.endswith(":1051")
    finally:
        dd.close()


def test_gossip_discovery_two_nodes():
    """memberlist analog: two UDP gossipers find each other and detect
    departure."""
    got_a, got_b = [], []
    pa, pb = free_port(), free_port()
    a = GossipDiscovery(
        got_a.append, f"127.0.0.1:{pa}",
        PeerInfo(grpc_address="127.0.0.1:9001"), [f"127.0.0.1:{pb}"],
        interval_ms=50, suspect_ms=400)
    b = GossipDiscovery(
        got_b.append, f"127.0.0.1:{pb}",
        PeerInfo(grpc_address="127.0.0.1:9002"), [f"127.0.0.1:{pa}"],
        interval_ms=50, suspect_ms=400)
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            if (got_a and len(got_a[-1]) == 2
                    and got_b and len(got_b[-1]) == 2):
                break
            time.sleep(0.05)
        assert len(got_a[-1]) == 2, "a never saw b"
        assert len(got_b[-1]) == 2, "b never saw a"
        # departure: close b; a must drop it after suspect_ms
        b.close()
        deadline = time.time() + 5
        while time.time() < deadline and len(got_a[-1]) != 1:
            time.sleep(0.05)
        assert len(got_a[-1]) == 1, "a never dropped departed b"
    finally:
        a.close()
        b.close()


def test_unknown_discovery_type():
    cfg = DaemonConfig(peer_discovery_type="carrier-pigeon")
    from gubernator_tpu.discovery import make_discovery

    with pytest.raises(ValueError):
        make_discovery(cfg, PeerInfo(grpc_address="x:1"), lambda p: None)


def test_standard_grpc_health_protocol(mesh):
    """grpc.health.v1.Health/Check — what k8s gRPC probes and
    grpc_health_probe speak — must answer SERVING on a healthy
    daemon.  Wire: response field 1 varint ServingStatus."""
    import grpc as _grpc

    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.netutil import free_port

    d = spawn_daemon(DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address="", cache_size=1 << 10), mesh=mesh)
    try:
        ch = _grpc.insecure_channel(f"127.0.0.1:{d.grpc_port}")
        call = ch.unary_unary("/grpc.health.v1.Health/Check")
        # empty request (overall health) and a named service both serve
        assert call(b"", timeout=30) == b"\x08\x01"
        assert call(b"\x0a\x10pb.gubernator.V1", timeout=30) == b"\x08\x01"
        # Watch (server-streaming): first message is the current status
        # immediately; the stream stays open (no second message until a
        # status change), ended by client cancel
        watch = ch.unary_stream("/grpc.health.v1.Health/Watch")
        stream = watch(b"", timeout=30)
        assert next(stream) == b"\x08\x01"
        # concurrent watchers are capped (thread-per-stream on a sync
        # server): the 5th gets RESOURCE_EXHAUSTED instead of parking
        # another worker thread forever
        extra = [watch(b"", timeout=30) for _ in range(3)]
        for s in extra:
            assert next(s) == b"\x08\x01"
        denied = watch(b"", timeout=30)
        with pytest.raises(_grpc.RpcError) as ei:
            next(denied)
        assert ei.value.code() == _grpc.StatusCode.RESOURCE_EXHAUSTED
        for s in [stream, *extra]:
            s.cancel()
        ch.close()
    finally:
        d.close()

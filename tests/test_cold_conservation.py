"""Unwarmed concurrent cold-key conservation (the PR-5 ROADMAP debt).

16 threads hammer a small set of BRAND-NEW keys with 1-row batches
through daemons 0 AND 1 of a 3-daemon cluster — no pre-warm — and
every hit must debit exactly once cluster-wide.  Pre-fix, forwarded
rows applied at the owner's wall clock while locally-served rows
applied at the caller's pinned ``now``: two time bases in one bucket
row, and the later base read the earlier-base row as EXPIRED → bucket
reset → 10-30% of the hits silently vanished per run (callers still
got success responses).  Warming each key first masked the loss, which
is why the PR-3 conservation test (which warms) never saw it.

The fix forwards the caller's accepted-at clock (created_at, proto
field 10) on the forward hop and the deferred hit queues; the
``GUBER_CREATED_AT_FWD=0`` escape restores the pre-fix behavior so the
loss stays demonstrable (tools/racer.py --no-created-at, and the
sharpness test below).
"""
import threading

import numpy as np
import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitRequest

pytest.importorskip("gubernator_tpu.ops._native",
                    reason="clustered wire lanes need the C++ codec")

DAY = 24 * 3_600_000
#: pinned far from the wall clock so any lane substituting its own
#: clock for the caller's time base breaks conservation VISIBLY
NOW0 = 1_750_000_000_000
LIMIT = 10 ** 6
N_THREADS, REPS, HITS, N_KEYS = 16, 4, 2, 10


def serialize(reqs):
    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        m.name = r.name
        m.unique_key = r.unique_key
        m.hits = r.hits
        m.limit = r.limit
        m.duration = r.duration
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
        m.burst = r.burst
    return msg.SerializeToString()


def one(hits, key, name):
    return serialize([RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=LIMIT,
        duration=DAY)])


def _run_cold(c, tag: str, lane: str) -> tuple[int, int]:
    """One unwarmed run over fresh keys; returns (sent, debited)."""
    name = f"coldcons-{tag}"
    keys = [f"coldcons-{tag}-{i}" for i in range(N_KEYS)]
    # warm ENGINES with an unrelated key (compile cost must not
    # serialize the schedule) — never the keys under test
    for d in range(3):
        c.instance_at(d).get_rate_limits_wire(
            one(0, f"warmup-{tag}", name), now_ms=NOW0)
    errs: list = []
    barrier = threading.Barrier(N_THREADS)

    def worker(t):
        inst = c.instance_at(t % 2)  # both entry daemons
        try:
            barrier.wait(timeout=60)
            for r in range(REPS):
                key = keys[(t + r) % N_KEYS]
                if lane == "wire":
                    out = pb.GetRateLimitsResp.FromString(
                        inst.get_rate_limits_wire(one(HITS, key, name),
                                                  now_ms=NOW0 + 1 + r))
                    err = out.responses[0].error
                else:
                    resp = inst.get_rate_limits(
                        [RateLimitRequest(name=name, unique_key=key,
                                          hits=HITS, limit=LIMIT,
                                          duration=DAY)],
                        now_ms=NOW0 + 1 + r)[0]
                    err = resp.error
                assert not err, err
        except Exception as e:  # noqa: BLE001 - audited below
            errs.append(repr(e))

    ths = [threading.Thread(target=worker, args=(t,))
           for t in range(N_THREADS)]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=120)
    assert not any(th.is_alive() for th in ths), "stuck caller"
    assert not errs, errs[:3]
    total = 0
    for k in keys:
        q = pb.GetRateLimitsResp.FromString(
            c.instance_at(0).get_rate_limits_wire(one(0, k, name),
                                                  now_ms=NOW0 + 1000))
        assert q.responses[0].error == ""
        total += LIMIT - int(q.responses[0].remaining)
    return N_THREADS * REPS * HITS, total


class TestUnwarmedColdKeyConservation:
    def test_wire_lane_exact(self):
        c = cluster_mod.start(3)
        try:
            for run in range(2):
                sent, debited = _run_cold(c, f"w{run}", "wire")
                assert debited == sent, \
                    f"run {run}: cold-key conservation broken " \
                    f"(wire lane): {debited} != {sent}"
        finally:
            c.stop()

    def test_object_lane_exact(self):
        c = cluster_mod.start(3)
        try:
            sent, debited = _run_cold(c, "obj", "object")
            assert debited == sent, \
                f"cold-key conservation broken (object lane): " \
                f"{debited} != {sent}"
        finally:
            c.stop()

    def test_prefix_behavior_still_loses(self, monkeypatch):
        """Sharpness: with caller-clock forwarding disabled
        (GUBER_CREATED_AT_FWD=0 — the pre-fix behavior) the same
        schedule LOSES hits.  If this starts passing, the regression
        tests above have stopped exercising the failure mode."""
        monkeypatch.setenv("GUBER_CREATED_AT_FWD", "0")
        c = cluster_mod.start(3)
        try:
            lost = 0
            for run in range(2):
                sent, debited = _run_cold(c, f"pre{run}", "wire")
                assert debited <= sent
                lost += sent - debited
            assert lost > 0, \
                "pre-fix behavior no longer reproduces the loss — " \
                "the conservation tests above are no longer sharp"
        finally:
            c.stop()

"""Doc-example smoke tests (reference: examples_test.go — BASELINE
config 1's named source)."""
import runpy
import sys


def test_single_daemon_example(capsys):
    runpy.run_path("examples/single_daemon.py", run_name="__main__")
    out = capsys.readouterr().out
    assert "status=UNDER_LIMIT" in out
    assert "remaining=9" in out


def test_embedded_engine_example(capsys):
    runpy.run_path("examples/embedded_engine.py", run_name="__main__")
    out = capsys.readouterr().out
    assert "decisions in" in out


def test_global_hotset_example():
    import runpy

    runpy.run_path("examples/global_hotset.py", run_name="__main__")


def test_pallas_serving_example(capsys, monkeypatch):
    monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
    runpy.run_path("examples/pallas_serving.py", run_name="__main__")
    out = capsys.readouterr().out
    assert "over the kernel" in out
    assert "under_limit=512" in out
    assert "bucket saturation 0/" in out

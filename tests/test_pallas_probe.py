"""tools/pallas_probe.py smoke (ISSUE 8 satellite): the kernel
bisection probe promised at PERF.md §(pallas) must run end-to-end on
this image — toy kernel, real decision kernel, and the fused serving
program each attributable separately, so an on-chip regression bisects
to environment vs kernel vs fusion vs size."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE = os.path.join(REPO, "tools", "pallas_probe.py")


def test_probe_smoke_all_stages_ok(tmp_path):
    out = str(tmp_path / "probe.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               GUBER_PALLAS_PROBE_OUT=out)
    r = subprocess.run([sys.executable, PROBE, "--smoke"], env=env,
                       cwd=REPO, timeout=420, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE)
    assert r.returncode == 0, r.stderr.decode()[-500:]
    with open(out) as f:
        res = json.load(f)
    assert res["smoke"] is True
    for stage in ("toy", "kernel_small", "fused_small"):
        assert res[stage]["ok"] is True, (stage, res[stage])
    # the stages actually measured something attributable
    assert res["kernel_small"]["out"]["decisions_per_s"] > 0
    assert res["fused_small"]["out"]["tap_rows_served"] > 0
    assert res["fused_small"]["out"]["fused_waves"] >= 1

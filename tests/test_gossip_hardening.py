"""Gossip membership under adversity (VERDICT r1 items 3/8): packet
loss must not flap membership (SWIM suspicion + indirect probes before
eviction), dead members must be evicted and STAY evicted (no
hearsay-refresh ghost loop), and joiners must converge via the
first-contact state push, not heartbeat osmosis.
"""
import random
import threading
import time

import pytest

from gubernator_tpu.discovery import GossipDiscovery
from gubernator_tpu.types import PeerInfo


class Recorder:
    """Thread-safe on_change history."""

    def __init__(self):
        self.mu = threading.Lock()
        self.history = []

    def __call__(self, peers):
        with self.mu:
            self.history.append(
                (time.monotonic(), sorted(p.grpc_address for p in peers)))

    def latest(self):
        with self.mu:
            return self.history[-1][1] if self.history else []

    def since(self, t0):
        with self.mu:
            return [(t, m) for t, m in self.history if t >= t0]


def wait_until(pred, timeout=10.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def spawn(n, interval_ms=100, suspect_ms=400, dead_ms=1200, seeds=None):
    """n gossip nodes on loopback; node i's grpc identity is g{i}."""
    nodes, recs = [], []
    for i in range(n):
        rec = Recorder()
        node = GossipDiscovery(
            rec, "127.0.0.1:0", PeerInfo(grpc_address=f"10.0.0.{i}:81"),
            known_hosts=list(seeds or []), interval_ms=interval_ms,
            suspect_ms=suspect_ms, dead_ms=dead_ms)
        if seeds is None and nodes:
            node._seeds = [nodes[0].gossip_addr]
        elif seeds is None:
            pass
        nodes.append(node)
        recs.append(rec)
    # everyone seeds off node 0
    for node in nodes[1:]:
        if not node._seeds:
            node._seeds = [nodes[0].gossip_addr]
    return nodes, recs


def make_lossy(node, p, seed):
    """Drop fraction p of this node's outbound datagrams."""
    rng = random.Random(seed)
    orig = node._send

    def lossy(addr, payload):
        if rng.random() < p:
            return
        orig(addr, payload)

    node._send = lossy


ALL3 = ["10.0.0.0:81", "10.0.0.1:81", "10.0.0.2:81"]


class TestGossipHardening:
    def test_stable_membership_under_30pct_loss(self):
        nodes, recs = spawn(3)
        try:
            assert wait_until(
                lambda: all(r.latest() == ALL3 for r in recs), 15), \
                [r.latest() for r in recs]
            # now drop 30% of every node's outbound datagrams
            for i, node in enumerate(nodes):
                make_lossy(node, 0.30, seed=100 + i)
            t0 = time.monotonic()
            time.sleep(3.0)  # ~7 suspect windows, ~2.5 dead windows
            # zero spurious re-homes: no notification since t0 may lack
            # a live member (suspicion + indirect probes must absorb
            # the loss)
            for i, rec in enumerate(recs):
                for t, members in rec.since(t0):
                    assert members == ALL3, (
                        f"node {i} flapped at +{t - t0:.2f}s: {members}")
        finally:
            for node in nodes:
                node.close()

    def test_dead_member_evicted_and_stays_dead(self):
        nodes, recs = spawn(3)
        try:
            assert wait_until(
                lambda: all(r.latest() == ALL3 for r in recs), 15)
            nodes[2].close()
            two = ALL3[:2]
            # evicted within a few dead windows (dead_ms=1200)
            assert wait_until(
                lambda: recs[0].latest() == two
                and recs[1].latest() == two, 10), \
                (recs[0].latest(), recs[1].latest())
            # the ghost-member loop: A and B keep gossiping each other —
            # the dead node must NOT reappear from hearsay
            t0 = time.monotonic()
            time.sleep(1.5)
            for i in (0, 1):
                for t, members in recs[i].since(t0):
                    assert members == two, (
                        f"ghost member resurrected on node {i}: {members}")
        finally:
            for node in nodes:
                node.close()

    def test_joiner_converges_via_state_push(self):
        nodes, recs = spawn(2)
        try:
            two = ALL3[:2]
            assert wait_until(
                lambda: all(r.latest() == two for r in recs), 15)
            rec3 = Recorder()
            t0 = time.monotonic()
            node3 = GossipDiscovery(
                rec3, "127.0.0.1:0",
                PeerInfo(grpc_address="10.0.0.2:81"),
                known_hosts=[nodes[0].gossip_addr],  # seeded with A only
                interval_ms=100, suspect_ms=400, dead_ms=1200)
            nodes.append(node3)
            # C must learn B (whom it was never seeded with) via A's
            # first-contact state push — well inside a handful of
            # intervals, not via eventual heartbeat osmosis
            assert wait_until(lambda: rec3.latest() == ALL3, 5), \
                rec3.latest()
            assert time.monotonic() - t0 < 5
            assert wait_until(
                lambda: all(r.latest() == ALL3 for r in recs[:2]), 10)
        finally:
            for node in nodes:
                node.close()

    def test_one_lossy_path_does_not_evict(self):
        """Asymmetric failure: A stops hearing C directly, but B still
        relays — the indirect probe (ping-req via B; C acks A directly)
        must keep C a member at A."""
        nodes, recs = spawn(3)
        try:
            assert wait_until(
                lambda: all(r.latest() == ALL3 for r in recs), 15)
            # C drops everything it would send DIRECTLY to A, except
            # acks (the indirect-probe response path stays open)
            a_addr = nodes[0].gossip_addr
            orig = nodes[2]._send

            def filtered(addr, payload):
                if addr == a_addr and b'"ack"' not in payload:
                    return
                orig(addr, payload)

            nodes[2]._send = filtered
            t0 = time.monotonic()
            time.sleep(3.0)
            for t, members in recs[0].since(t0):
                assert members == ALL3, (
                    f"A evicted C despite the indirect path: {members}")
        finally:
            for node in nodes:
                node.close()


class TestPartitionHeal:
    def test_healed_partition_remerges_without_seeds(self):
        """A full partition longer than dead_ms evicts both directions.
        After the network heals, the rejoin probes to retained dead
        members must re-merge the cluster even when no static seed
        spans the cut (memberlist's dead-node reconnect behavior)."""
        nodes, recs = spawn(3)
        try:
            assert wait_until(
                lambda: all(r.latest() == ALL3 for r in recs), 15)
            # no seed spans the cut: C keeps no seeds at all, and A/B
            # were never seeded with C
            nodes[2]._seeds = []
            # cut {A,B} <-> {C} in both directions
            c_addr = nodes[2].gossip_addr
            ab_addrs = {nodes[0].gossip_addr, nodes[1].gossip_addr}
            originals = [n._send for n in nodes]

            def cut(node, blocked):
                orig = node._send

                def f(addr, payload, _orig=orig, _blocked=blocked):
                    if addr in _blocked:
                        return
                    _orig(addr, payload)

                node._send = f

            cut(nodes[0], {c_addr})
            cut(nodes[1], {c_addr})
            cut(nodes[2], ab_addrs)
            two, solo = ALL3[:2], ALL3[2:]
            assert wait_until(
                lambda: recs[0].latest() == two
                and recs[1].latest() == two
                and recs[2].latest() == solo, 15), \
                (recs[0].latest(), recs[2].latest())
            # heal: restore the original senders
            for node, orig in zip(nodes, originals):
                node._send = orig
            # re-merge must come from the rejoin probes (C has no seeds
            # and neither side has the other as a member any more)
            assert wait_until(
                lambda: all(r.latest() == ALL3 for r in recs), 15), \
                [r.latest() for r in recs]
        finally:
            for node in nodes:
                node.close()

    def test_dead_retention_is_bounded(self):
        """Dead entries expire after dead_retain_s — a departed node
        does not collect rejoin probes forever."""
        rec0, rec1 = Recorder(), Recorder()
        n0 = GossipDiscovery(
            rec0, "127.0.0.1:0", PeerInfo(grpc_address="10.0.0.0:81"),
            known_hosts=[], interval_ms=100, suspect_ms=300, dead_ms=900,
            dead_retain_ms=1500)
        n1 = GossipDiscovery(
            rec1, "127.0.0.1:0", PeerInfo(grpc_address="10.0.0.1:81"),
            known_hosts=[n0.gossip_addr], interval_ms=100,
            suspect_ms=300, dead_ms=900)
        try:
            two = ALL3[:2]
            assert wait_until(
                lambda: rec0.latest() == two and rec1.latest() == two, 15)
            n1.close()
            assert wait_until(lambda: rec0.latest() == two[:1], 10)
            assert wait_until(lambda: not n0._dead, 10), n0._dead
        finally:
            n0.close()
            n1.close()


class TestMalformedDatagrams:
    def test_rx_survives_garbage(self):
        """Unauthenticated UDP: junk datagrams (bad JSON, wrong types,
        non-dict payloads) must neither kill the rx thread nor perturb
        membership."""
        import socket as _socket

        nodes, recs = spawn(2)
        try:
            two = ALL3[:2]
            assert wait_until(
                lambda: all(r.latest() == two for r in recs), 15)
            s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
            host, _, port = nodes[0].gossip_addr.rpartition(":")
            tgt = (host, int(port))
            for payload in (b"\xff\x00garbage", b"[1,2,3]", b'"str"',
                            b'{"t":"ping-req","from":"x","target":123}',
                            b'{"t":"ping","from":42}',
                            b'{"from":"x:1","members":[1,2]}',
                            # well-formed JSON, poisonous values: a null
                            # info must not enter the member map (it
                            # would crash every later notify) and a
                            # non-dict sender entry must not be stored
                            b'{"members":{"1.2.3.4:9":null}}',
                            b'{"from":"9.9.9.9:1","members":'
                            b'{"9.9.9.9:1":"notadict"}}'):
                s.sendto(payload, tgt)
            s.close()
            time.sleep(1.0)
            # rx thread alive and membership still exact
            assert nodes[0]._rx.is_alive()
            assert recs[0].latest() == two, recs[0].latest()
            # and the node still processes real traffic afterwards
            t0 = time.monotonic()
            assert wait_until(
                lambda: all(r.latest() == two for r in recs), 5)
        finally:
            for node in nodes:
                node.close()

"""The writeback scatters' promises to the backend are verified, not
assumed.

core/step.py declares ``unique_indices=True`` + ``indices_are_sorted=
True`` on the table-writeback scatters (the countermeasure to the TPU
backend's serialized-scatter lowering, 2026-08-01).  Both are undefined
behavior if false, and a CPU parity run would NOT catch a lie — XLA:CPU
does not exploit the hints.  This test flips the step's trace-time
check hook so every executed step records any wrow vector that is not
strictly ascending (ascending + no duplicates ⇔ both promises), then
drives the shapes most likely to break the invariant:

- duplicate keys (many requests → one segment → one writer)
- fresh inserts (winner-claimed rows mixed with existing rows)
- table overfull (err rows are remapped to cap and sort LAST
  into a non-exists segment)
- invalid rows and mixed arrival times (the two-key sort path)
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gubernator_tpu.core import step as step_mod
from gubernator_tpu.core.batch import RequestBatch
from gubernator_tpu.core.step import decide_batch
from gubernator_tpu.core.table import init_table

i64 = jnp.int64
NOW = 1_760_000_000_000


def _mk(keys, now_col=None, valid=None):
    n = len(keys)
    return RequestBatch(
        key=jnp.asarray(np.asarray(keys, dtype=np.uint64)),
        hits=jnp.ones(n, i64), limit=jnp.full(n, 5, i64),
        duration=jnp.full(n, 10_000, i64), eff_ms=jnp.full(n, 10_000, i64),
        greg_end=jnp.zeros(n, i64), behavior=jnp.zeros(n, jnp.int32),
        algorithm=jnp.zeros(n, jnp.int32), burst=jnp.full(n, 5, i64),
        valid=jnp.asarray(valid if valid is not None else [True] * n),
        now=None if now_col is None else jnp.asarray(now_col, i64))


@pytest.fixture()
def invariant_hook():
    jax.clear_caches()  # cached traces predate the hook
    step_mod._CHECK_SCATTER_INVARIANTS = True
    step_mod._SCATTER_INVARIANT_VIOLATIONS.clear()
    for k in step_mod._SCATTER_INVARIANT_CHECKS:
        step_mod._SCATTER_INVARIANT_CHECKS[k] = 0
    yield step_mod._SCATTER_INVARIANT_VIOLATIONS
    step_mod._CHECK_SCATTER_INVARIANTS = False
    jax.clear_caches()


def test_wrow_strictly_ascending_under_adversarial_batches(invariant_hook):
    rng = np.random.default_rng(5)
    st = init_table(1 << 8)  # small: forces collisions and overfull errs

    # duplicates + inserts + growing occupancy
    for t in range(6):
        keys = (rng.integers(1, 300, size=128)).astype(np.uint64)
        st, out = decide_batch(st, _mk(keys), jnp.asarray(NOW + t, i64))
    # overfull: distinct keys far beyond capacity → err rows (row -1)
    keys = np.arange(1, 513, dtype=np.uint64) * 7919
    st, out = decide_batch(st, _mk(keys), jnp.asarray(NOW + 10, i64))
    assert bool(out.err.any()), "expected table-full err rows"
    # invalid rows + mixed arrival times (the two-key sort path)
    keys = rng.integers(1, 50, size=128).astype(np.uint64)
    nows = NOW + 20 + rng.integers(0, 5, size=128)
    valid = rng.random(128) > 0.2
    st, out = decide_batch(st, _mk(keys, now_col=nows, valid=valid),
                           jnp.asarray(NOW + 20, i64))
    # complex tails (duplicate keys + per-request flags) drive the
    # while_loop body whose idxj scatter also promises unique_indices
    keys_c = np.repeat(rng.integers(1, 9, size=16), 8).astype(np.uint64)
    bc = _mk(keys_c)
    from gubernator_tpu.types import Behavior
    beh = np.zeros(128, np.int32)
    # RESET_REMAINING on some duplicates → segment not simple
    beh[::3] = int(Behavior.RESET_REMAINING)
    bc = bc._replace(behavior=jnp.asarray(beh))
    st, out = decide_batch(st, bc, jnp.asarray(NOW + 30, i64))
    jax.block_until_ready(out.status)
    jax.effects_barrier()  # debug.callback effects are NOT flushed by
    # block_until_ready on async backends

    counts = step_mod._SCATTER_INVARIANT_CHECKS
    assert counts["wrow"] >= 8, (
        "the wrow trace-time hook never fired — the test is vacuous")
    # _insert runs INSERT_ROUNDS claim scatters per step; body_fn fires
    # whenever a complex tail iterates (the mixed-now batch above).
    # Every unique_indices promise site must have been exercised, or
    # this test silently stops covering it (ADVICE r3 item 2).
    assert counts["insert_tkey"] >= 8, counts
    assert counts["body_idxj"] >= 1, counts
    assert not invariant_hook, (
        f"{len(invariant_hook)} index vectors broke the scatter "
        f"promises; first: {invariant_hook[0] if invariant_hook else None}")

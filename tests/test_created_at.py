"""created_at (RateLimitReq field 10) — the caller-clock forward stamp.

A request's time base must travel WITH the request: the forward hop,
the degraded-mode reconcile queues, and the cross-region queues all
apply hits on another daemon LATER, and applying them at that daemon's
then-clock on a row living on the caller's base reads as expired —
bucket reset, debits silently gone (the concurrent cold-key
conservation loss).  These tests pin the codec plumbing end to end:
object ↔ TLV round trips, the C++ parser/packer, the bulk forward
stamp, and the packers' now-column override.
"""
import numpy as np
import pytest

from gubernator_tpu.core.batch import pack_columns, pack_requests
from gubernator_tpu.hashing import hash_request_keys
from gubernator_tpu.types import RateLimitRequest
from gubernator_tpu.wire import (req_from_tlv, req_to_tlv,
                                 tlv_created_at_payload, tlv_with_created)

DAY = 24 * 3_600_000
T0 = 1_700_000_000_000


def _req(key="k", created=0, hits=3):
    return RateLimitRequest(name="ca", unique_key=key, hits=hits,
                            limit=100, duration=DAY, created_at=created)


class TestWireCodec:
    def test_tlv_round_trip_carries_created_at(self):
        r = _req(created=T0 + 5)
        back = req_from_tlv(req_to_tlv(r))
        assert back.created_at == T0 + 5
        assert (back.name, back.unique_key, back.hits) == ("ca", "k", 3)

    def test_unset_created_at_stays_unset(self):
        back = req_from_tlv(req_to_tlv(_req(created=0)))
        assert back.created_at == 0

    def test_tlv_with_created_stamps_unstamped_slice(self):
        tlv = req_to_tlv(_req(created=0))
        stamped = tlv_with_created(tlv, T0 + 9)
        assert req_from_tlv(stamped).created_at == T0 + 9
        # other fields untouched
        assert req_from_tlv(stamped).hits == 3

    def test_payload_scan_last_value_wins(self):
        # proto3 scalar semantics: a second field-10 varint overrides
        tlv = tlv_with_created(req_to_tlv(_req(created=T0)), T0 + 77)
        assert req_from_tlv(tlv).created_at == T0 + 77

    def test_payload_scan_handles_all_wire_types(self):
        r = _req(created=T0 + 1)
        r.metadata["trace"] = "abc"  # length-delimited field 9
        payload = req_to_tlv(r)
        assert req_from_tlv(payload).created_at == T0 + 1
        assert tlv_created_at_payload(b"") == 0


class TestNativeCodec:
    @pytest.fixture(autouse=True)
    def _native(self):
        pytest.importorskip("gubernator_tpu.ops._native",
                            reason="needs the C++ codec")

    def test_parse_returns_created_column(self):
        from gubernator_tpu.ops import native

        data = req_to_tlv(_req("a", created=T0 + 3)) + \
            req_to_tlv(_req("b", created=0))
        parsed = native.parse_get_rate_limits(data)
        assert parsed is not None
        assert parsed["created_at"].tolist() == [T0 + 3, 0]

    def test_stamp_req_tlvs_stamps_only_unstamped(self):
        from gubernator_tpu.ops import native

        data = req_to_tlv(_req("a", created=T0 + 3)) + \
            req_to_tlv(_req("b", created=0))
        parsed = native.parse_get_rate_limits(data)
        out = native.stamp_req_tlvs(
            data, parsed["tlv_off"], parsed["tlv_len"],
            parsed["created_at"], T0 + 50)
        reparsed = native.parse_get_rate_limits(out)
        # first slice keeps the caller stamp (first hop wins), second
        # gets the forwarder's
        assert reparsed["created_at"].tolist() == [T0 + 3, T0 + 50]
        assert reparsed["hits"].tolist() == parsed["hits"].tolist()

    def test_pack_wire_wave_now_prefers_created(self):
        from gubernator_tpu.core.batch import WaveBufferPool
        from gubernator_tpu.ops import native

        data = req_to_tlv(_req("a", created=T0 + 3)) + \
            req_to_tlv(_req("b", created=0))
        lease = WaveBufferPool().lease(64)
        res = native.pack_wire_wave(data, T0 + 99, lease.a64, lease.a32)
        assert res is not None
        n = res[0]
        assert n == 2
        assert lease.a64[7][:2].tolist() == [T0 + 3, T0 + 99]
        lease.release()

    def test_pb2_fallback_paths_still_parse_stamped_tlvs(self):
        # pb2 treats field 10 as an unknown field: parses cleanly, and
        # the hand scan in req_from_tlv recovers the value
        from gubernator_tpu.proto import gubernator_pb2 as pb

        tlv = tlv_with_created(req_to_tlv(_req(created=0)), T0 + 4)
        msg = pb.GetRateLimitsReq.FromString(tlv)
        assert msg.requests[0].hits == 3


class TestPackers:
    def test_pack_requests_honors_created_at(self):
        reqs = [_req("a", created=T0 + 7), _req("b", created=0)]
        kh = hash_request_keys([r.name for r in reqs],
                               [r.unique_key for r in reqs])
        b, errs = pack_requests(reqs, T0 + 99, size=2, key_hashes=kh)
        assert not any(errs)
        assert b.now[:2].tolist() == [T0 + 7, T0 + 99]

    def test_pack_columns_honors_created_at(self):
        n = 3
        kh = np.arange(1, n + 1, dtype=np.uint64)
        z = np.zeros(n, np.int64)
        created = np.array([0, T0 + 5, 0], np.int64)
        b, errs = pack_columns(kh, z + 1, z + 10, z + DAY, z.copy(),
                               np.zeros(n, np.int32), z.copy(), T0 + 99,
                               created_at=created)
        assert not errs
        assert b.now.tolist() == [T0 + 99, T0 + 5, T0 + 99]

    def test_pack_columns_without_created_matches_legacy(self):
        n = 2
        kh = np.arange(1, n + 1, dtype=np.uint64)
        z = np.zeros(n, np.int64)
        b, _ = pack_columns(kh, z + 1, z + 10, z + DAY, z.copy(),
                            np.zeros(n, np.int32), z.copy(), T0)
        assert b.now.tolist() == [T0, T0]

"""PallasServingEngine: the Mosaic kernel as a deployable serving mode.

Engine-protocol parity vs ShardedEngine (the XLA mode) on shared
request streams — decisions, sweep, row ops, snapshot/restore — plus
the domain gate.  Runs the kernel in interpret mode on CPU (same
reference interpreter as test_pallas_step.py)."""
import numpy as np
import pytest

import jax.numpy as jnp

from gubernator_tpu.hashing import hash_request_keys
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.pallas_engine import PallasServingEngine
from gubernator_tpu.types import RateLimitRequest

NOW = 1_765_000_000_000


def req(key, **kw):
    d = dict(hits=1, limit=10, duration=10_000)
    d.update(kw)
    return RateLimitRequest(name="pe", unique_key=key, **d)


@pytest.fixture()
def engines():
    mesh = make_mesh(n=2)
    pe = PallasServingEngine(mesh, capacity_per_shard=1 << 9,
                             batch_per_shard=64)
    xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                       batch_per_shard=64)
    return pe, xe


def both(engines, reqs, now):
    pe, xe = engines
    rp = pe.check_batch(reqs, now)
    rx = xe.check_batch(reqs, now)
    for i, (a, b) in enumerate(zip(rp, rx)):
        assert (int(a.status), a.remaining, a.reset_time, a.limit) == \
            (int(b.status), b.remaining, b.reset_time, b.limit), i
    return rp


class TestServingParity:
    def test_token_flow_and_counters(self, engines):
        pe, xe = engines
        reqs = [req(f"k{i % 6}", hits=2) for i in range(24)]
        both(engines, reqs, NOW)
        both(engines, reqs, NOW + 500)
        # deny region
        both(engines, reqs, NOW + 600)
        assert pe.over_count == xe.over_count
        assert pe.insert_count == xe.insert_count
        # expiry → fresh
        both(engines, reqs, NOW + 30_000)

    def test_leaky_flow(self, engines):
        reqs = [req(f"l{i % 4}", algorithm=1, hits=3, limit=100,
                    burst=100, duration=60_000) for i in range(16)]
        both(engines, reqs, NOW)
        both(engines, reqs, NOW + 2_000)
        both(engines, reqs, NOW + 90_000)

    def test_mixed_algorithms_and_flags(self, engines):
        rng = np.random.default_rng(3)
        reqs = []
        for i in range(48):
            alg = i % 2
            beh = 8 if i % 7 == 0 else (32 if i % 11 == 0 else 0)
            reqs.append(req(f"m{i % 9}", algorithm=alg,
                            hits=int(rng.integers(0, 4)),
                            limit=20, burst=20, behavior=beh))
        both(engines, reqs, NOW)
        both(engines, reqs, NOW + 100)

    def test_out_of_domain_rows_scoped_not_fatal(self, engines):
        """A row outside the kernel's value domain must not fail the
        wave (the dispatcher coalesces independent callers): it comes
        back unservable ('rate limit table full') while every other
        row serves normally — and the device state is untouched by it."""
        pe, _ = engines
        resps = pe.check_batch(
            [req("ok1", limit=5), req("big", limit=1 << 31),
             req("ok2", limit=5)], NOW)
        assert resps[0].error == "" and resps[0].remaining == 4
        assert resps[2].error == "" and resps[2].remaining == 4
        assert "full" in resps[1].error
        # the out-of-domain key left no row behind
        kh = hash_request_keys(["pe"], ["big"])
        found, _ = pe.gather_rows(kh)
        assert not found.any()

    def test_out_of_domain_rows_scoped_pipelined(self, engines):
        """Same scoping through the pipelined launch/sync pair (the
        TPU dispatcher path calls these directly)."""
        from gubernator_tpu.core.batch import pack_requests

        pe, _ = engines
        reqs = [req("p1", limit=5), req("huge", hits=1 << 31),
                req("p2", limit=5)]
        kh = hash_request_keys(["pe"] * 3, ["p1", "huge", "p2"])
        batch, _errs = pack_requests(reqs, NOW, size=3, key_hashes=kh)
        token = pe.launch_packed(batch, kh, NOW)
        st, lim, rem, rst, full = pe.sync_packed(token)
        assert list(full) == [False, True, False]
        assert rem[0] == 4 and rem[2] == 4

    def test_sweep_reclaims_expired(self, engines):
        pe, xe = engines
        reqs = [req(f"s{i}") for i in range(12)]
        both(engines, reqs, NOW)
        pe.sweep(NOW + 60_000)
        xe.sweep(NOW + 60_000)
        assert pe.live_rows == 0
        # the slots actually free again (fresh inserts succeed)
        both(engines, reqs, NOW + 61_000)

    def test_sweep_keeps_live_rows(self, engines):
        pe, _ = engines
        both(engines, [req(f"sl{i}") for i in range(5)], NOW)
        pe.sweep(NOW + 1_000)  # inside the 10s window
        assert pe.live_rows == 5


class TestRowOps:
    def test_gather_upsert_remove_roundtrip(self, engines):
        pe, xe = engines
        reqs = [req(f"r{i}", hits=4) for i in range(8)]
        both(engines, reqs, NOW)
        kh = hash_request_keys(["pe"] * 8,
                               [f"r{i}" for i in range(8)])
        fp, cp = pe.gather_rows(kh)
        fx, cx = xe.gather_rows(kh)
        assert fp.all() and fx.all()
        for f in ("meta", "limit", "remaining", "t_ms", "expire_at",
                  "duration", "eff_ms"):
            assert (cp[f] == cx[f]).all(), f
        # upsert modified state into BOTH engines → still in lockstep
        cp["remaining"] = cp["remaining"] + 3
        assert pe.upsert_rows(kh, cp) == 8
        assert xe.upsert_rows(kh, cp) == 8
        both(engines, [req(f"r{i}", hits=0) for i in range(8)], NOW + 10)
        # remove → keys re-insert fresh
        assert pe.remove_rows(kh[:4]) == 4
        assert xe.remove_rows(kh[:4]) == 4
        both(engines, reqs, NOW + 20)

    def test_gather_missing_keys(self, engines):
        pe, _ = engines
        kh = hash_request_keys(["pe"], ["never-seen"])
        found, _ = pe.gather_rows(kh)
        assert not found.any()


class TestSnapshotRestore:
    def test_snapshot_matches_xla_columns(self, engines):
        pe, xe = engines
        reqs = [req(f"ss{i}", hits=2) for i in range(10)]
        both(engines, reqs, NOW)
        sp = pe.snapshot()
        sx = xe.snapshot()
        op = np.argsort(sp["key"])
        ox = np.argsort(sx["key"])
        assert (sp["key"][op] == sx["key"][ox]).all()
        for f in ("meta", "limit", "remaining", "t_ms", "expire_at"):
            assert (sp[f][op] == sx[f][ox]).all(), f

    def test_restore_roundtrip_across_engine_kinds(self):
        """An XLA-engine snapshot restores into a pallas engine (and
        back): checkpoint/resume is layout-independent."""
        xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                           batch_per_shard=64)
        reqs = [req(f"x{i}", hits=3) for i in range(9)]
        xe.check_batch(reqs, NOW)
        snap = xe.snapshot()

        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        assert pe.restore(snap) == 9
        # restored counters serve identically
        q = [req(f"x{i}", hits=0) for i in range(9)]
        rp = pe.check_batch(q, NOW + 5)
        rx = xe.check_batch(q, NOW + 5)
        for a, b in zip(rp, rx):
            assert (int(a.status), a.remaining) == \
                (int(b.status), b.remaining)
        # and back: pallas snapshot → fresh XLA engine
        xe2 = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                            batch_per_shard=64)
        assert xe2.restore(pe.snapshot()) == 9
        rx2 = xe2.check_batch(q, NOW + 6)
        rp2 = pe.check_batch(q, NOW + 6)
        for a, b in zip(rx2, rp2):
            assert (int(a.status), a.remaining) == \
                (int(b.status), b.remaining)

    def test_restore_drops_leaky_td_out_of_domain(self):
        """ADVICE r4 (medium): leaky remaining is stored in td units
        (remaining x eff) and an XLA-engine snapshot clamps burst only
        to TD_BOUND//eff, so td can reach ~2^61 — far past the kernel
        divider's td < 2^30*eff precondition.  Such rows must DROP on
        restore (counted), not serve garbage quotients."""
        xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                           batch_per_shard=64)
        xe.check_batch(
            [req("bigleaky", algorithm=1, limit=5, burst=1 << 31,
                 duration=60_000),
             req("okleaky", algorithm=1, limit=5, burst=5,
                 duration=60_000)], NOW)
        snap = xe.snapshot()
        # the snapshot really does carry an out-of-domain td
        from gubernator_tpu.ops import pallas_step as ps
        assert (snap["remaining"] >= ps.VALUE_BOUND * 60_000).any()

        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        assert pe.restore(snap) == 1
        assert pe.dropped_rows == 1
        kh = hash_request_keys(["pe", "pe"], ["bigleaky", "okleaky"])
        found, cols = pe.gather_rows(kh)
        assert list(found) == [False, True]
        # the surviving row's td round-tripped exactly
        ok_td = snap["remaining"][
            snap["remaining"] < ps.VALUE_BOUND * 60_000][0]
        assert cols["remaining"][1] == ok_td

    def test_valid_write_survives_invalid_late_duplicate(self):
        """A sequential walk validates per OCCURRENCE: an out-of-domain
        late duplicate must not shadow an earlier valid write of the
        same key (caught by review of the vectorized rewrite — dedupe
        must run after domain filtering, not before)."""
        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        kh = hash_request_keys(["pe"], ["dupkey"])
        keys = np.concatenate([kh, kh]).astype(np.uint64)
        n = 2
        arrays = {"meta": np.zeros(n, np.int32),
                  "limit": np.array([5, 1 << 40], np.int64),
                  "burst": np.full(n, 5, np.int64),
                  "remaining": np.array([3, 4], np.int64),
                  "duration": np.full(n, 60_000, np.int64),
                  "eff_ms": np.full(n, 60_000, np.int64),
                  "t_ms": np.full(n, NOW, np.int64),
                  "expire_at": np.full(n, NOW + 60_000, np.int64)}
        assert pe.upsert_rows(keys, arrays) == 1
        assert pe.dropped_rows == 1
        found, cols = pe.gather_rows(kh)
        assert found.all()
        assert cols["remaining"][0] == 3  # the valid occurrence's value
        # restore path: same contract
        pe2 = PallasServingEngine(make_mesh(n=2),
                                  capacity_per_shard=1 << 9,
                                  batch_per_shard=64)
        arrays2 = dict(arrays)
        arrays2["key"] = keys
        assert pe2.restore(arrays2) == 1
        found2, cols2 = pe2.gather_rows(kh)
        assert found2.all() and cols2["remaining"][0] == 3

    def test_duplicate_valid_occurrences_count_per_occurrence(self):
        """Sequential accounting: a Loader emitting the same key twice
        (merged snapshots) applies last-write-wins, and BOTH
        occurrences count as restored — 'restored 1/2' would read as
        data loss to an operator."""
        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        kh = hash_request_keys(["pe"], ["twice"])
        keys = np.concatenate([kh, kh]).astype(np.uint64)
        n = 2
        arrays = {"key": keys,
                  "meta": np.zeros(n, np.int32),
                  "limit": np.full(n, 10, np.int64),
                  "burst": np.full(n, 10, np.int64),
                  "remaining": np.array([7, 4], np.int64),
                  "duration": np.full(n, 60_000, np.int64),
                  "eff_ms": np.full(n, 60_000, np.int64),
                  "t_ms": np.full(n, NOW, np.int64),
                  "expire_at": np.full(n, NOW + 60_000, np.int64)}
        assert pe.restore(arrays) == 2
        assert pe.dropped_rows == 0
        found, cols = pe.gather_rows(kh)
        assert found.all() and cols["remaining"][0] == 4  # last wins

    def test_restore_all_rows_invalid_is_a_noop(self):
        """Every row out-of-domain → no placement, drops counted, and
        the table is untouched (no pointless full-table re-upload)."""
        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        kh = hash_request_keys(["pe", "pe"], ["a", "b"])
        n = 2
        arrays = {"key": kh.astype(np.uint64),
                  "meta": np.zeros(n, np.int32),
                  "limit": np.full(n, 1 << 40, np.int64),
                  "burst": np.full(n, 5, np.int64),
                  "remaining": np.full(n, 3, np.int64),
                  "duration": np.full(n, 60_000, np.int64),
                  "eff_ms": np.full(n, 60_000, np.int64),
                  "t_ms": np.full(n, NOW, np.int64),
                  "expire_at": np.full(n, NOW + 60_000, np.int64)}
        before = pe.state
        assert pe.restore(arrays) == 0
        assert pe.dropped_rows == 2
        assert pe.state is before  # early-out: state object untouched

    def test_restore_drops_negative_leaky_td(self):
        """Negative leaky remaining (outside [0, 2^30*eff)) is equally
        out of the divider's domain and must drop."""
        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        kh = hash_request_keys(["pe"], ["negtd"])
        n = 1
        arrays = {"key": kh.astype(np.uint64),
                  "meta": np.full(n, 1, np.int32),
                  "limit": np.full(n, 5, np.int64),
                  "burst": np.full(n, 5, np.int64),
                  "remaining": np.full(n, -60_000, np.int64),
                  "duration": np.full(n, 60_000, np.int64),
                  "eff_ms": np.full(n, 60_000, np.int64),
                  "t_ms": np.full(n, NOW, np.int64),
                  "expire_at": np.full(n, NOW + 60_000, np.int64)}
        assert pe.restore(arrays) == 0
        assert pe.dropped_rows == 1

    def test_vectorized_placement_matches_sequential_reference(self):
        """Property check of the vectorized bucket placement against a
        per-row sequential walk: forced bucket collisions, duplicate
        keys (last write wins), updates of existing rows, and
        bucket-full drops all agree."""
        from gubernator_tpu.ops import pallas_step as ps
        from gubernator_tpu.parallel.pallas_engine import (
            _columns_to_words_batch, _dedupe_last, _place_into_buckets)

        rng = np.random.default_rng(11)
        n_buckets, n_keys = 4, 64  # heavy collisions: 16 keys/bucket avg
        for trial in range(20):
            keys = rng.integers(1, 1 << 62, n_keys).astype(np.uint64)
            # duplicates: re-use ~25% of keys
            dup = rng.integers(0, n_keys, n_keys // 4)
            keys[dup] = keys[(dup + 7) % n_keys]
            base = (keys % n_buckets).astype(np.int64) * ps.SLOTS
            arrays = {
                "meta": np.zeros(n_keys, np.int32),
                "limit": rng.integers(1, 100, n_keys),
                "burst": np.full(n_keys, 10, np.int64),
                "remaining": rng.integers(0, 100, n_keys),
                "duration": np.full(n_keys, 1000, np.int64),
                "eff_ms": np.full(n_keys, 1000, np.int64),
                "t_ms": np.full(n_keys, NOW, np.int64),
                "expire_at": np.full(n_keys, NOW + 1000, np.int64)}
            # pre-populate some buckets so update-vs-insert both occur
            table = np.zeros((n_buckets * ps.SLOTS, ps.WORDS), np.int32)
            pre = rng.choice(n_keys, 8, replace=False)
            w_pre, _ = _columns_to_words_batch(
                {f: v[pre] for f, v in arrays.items()}, keys[pre])
            for j, i in enumerate(pre):
                b0 = int(base[i])
                slot = rng.integers(0, ps.SLOTS)
                table[b0 + slot] = w_pre[j]

            # --- sequential reference on a copy ---
            ref = table.copy()
            ref_placed = 0
            words_all, valid_all = _columns_to_words_batch(arrays, keys)
            for i in range(n_keys):
                if not valid_all[i]:
                    continue
                b = ref[base[i]:base[i] + ps.SLOTS]
                klo = np.int32(np.uint32(keys[i] & 0xFFFFFFFF))
                khi = np.int32(np.uint32(keys[i] >> 32))
                hit = np.nonzero((b[:, ps.W_KLO] == klo)
                                 & (b[:, ps.W_KHI] == khi))[0]
                if hit.size:
                    b[hit[0]] = words_all[i]
                    ref_placed += 1
                    continue
                emp = np.nonzero((b[:, ps.W_KLO] == 0)
                                 & (b[:, ps.W_KHI] == 0))[0]
                if emp.size:
                    b[emp[0]] = words_all[i]
                    ref_placed += 1

            # --- vectorized path (validate → dedupe → place), the
            # same order as _prepared_rows ---
            words_v, valid_v = _columns_to_words_batch(arrays, keys)
            vkeys, words = keys[valid_v], words_v[valid_v]
            keep, _counts = _dedupe_last(vkeys)
            vkeys, words = vkeys[keep], words[keep]
            vbase = (vkeys % n_buckets).astype(np.int64) * ps.SLOTS
            ubase, gid = np.unique(vbase, return_inverse=True)
            vec = table.copy()
            uidx = ubase[:, None] + np.arange(ps.SLOTS)[None, :]
            buckets = vec[uidx]
            klo = vkeys.astype(np.uint32).astype(np.int32)
            khi = (vkeys >> np.uint64(32)).astype(
                np.uint32).astype(np.int32)
            placed = _place_into_buckets(buckets, gid, klo, khi, words)
            vec[uidx] = buckets

            # same final table contents, bucket by bucket, slot-order
            # independent (sort each bucket's rows)
            for b0 in range(0, n_buckets * ps.SLOTS, ps.SLOTS):
                rb = ref[b0:b0 + ps.SLOTS]
                vb = vec[b0:b0 + ps.SLOTS]
                assert (np.sort(rb.view([("", rb.dtype)] * ps.WORDS),
                                axis=0)
                        == np.sort(vb.view([("", vb.dtype)] * ps.WORDS),
                                   axis=0)).all(), (trial, b0)

    def test_restore_1m_rows_is_fast(self):
        """VERDICT r4 item 3 bound: a 1M-row snapshot restores in
        seconds (the old per-row loop took minutes).  Wall-clock bound
        is generous for a loaded 1-core CI host; the structural claim
        is 'no per-row Python'."""
        import time

        n = 1_000_000
        rng = np.random.default_rng(5)
        # full uint64 range: shard_of takes the TOP 32 bits, so keys
        # below 2^63 would all land in shard 0 and double bucket load
        keys = rng.integers(1, (1 << 64) - 1, n, dtype=np.uint64)
        keys = np.unique(keys)  # ~1M distinct
        n = len(keys)
        arrays = {"key": keys,
                  "meta": np.zeros(n, np.int32),
                  "limit": np.full(n, 100, np.int64),
                  "burst": np.full(n, 100, np.int64),
                  "remaining": rng.integers(0, 100, n),
                  "duration": np.full(n, 60_000, np.int64),
                  "eff_ms": np.full(n, 60_000, np.int64),
                  "t_ms": np.full(n, NOW, np.int64),
                  "expire_at": np.full(n, NOW + 60_000, np.int64)}
        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 20,
                                 batch_per_shard=64)
        t0 = time.monotonic()
        placed = pe.restore(arrays)
        dt = time.monotonic() - t0
        # every row is accounted for: placed or dropped (bucket full
        # at 0.5 load over 8-slot buckets loses a small tail)
        assert placed + pe.dropped_rows == n
        assert placed > 0.9 * n
        assert dt < 60, f"1M-row restore took {dt:.1f}s"
        # spot-check round-trip of a sample
        pick = rng.choice(n, 32, replace=False)
        found, cols = pe.gather_rows(keys[pick])
        ok = found  # bucket-full drops may hit the sample
        assert (cols["remaining"][ok]
                == arrays["remaining"][pick][ok]).all()

    def test_restore_drops_out_of_domain_rows(self):
        xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                           batch_per_shard=64)
        xe.check_batch([req("huge", limit=1 << 40),
                        req("ok", limit=5)], NOW)
        snap = xe.snapshot()
        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        assert pe.restore(snap) == 1
        assert pe.dropped_rows == 1


class TestStoreIntegration:
    def test_store_write_and_read_through_pallas_mode(self, monkeypatch):
        """The Store subsystem (persistence hooks) runs unchanged over
        the bucket layout: write-through sees mutations via the bucket
        row ops; a fresh pallas-mode instance read-through-seeds from
        persisted state."""
        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance
        from gubernator_tpu.store import CacheItem, MockStore
        from gubernator_tpu.types import RateLimitRequest

        monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)

        def sreq(**kw):
            d = dict(hits=1, limit=10, duration=60_000)
            d.update(kw)
            return RateLimitRequest(name="rt", unique_key="k1", **d)

        store = MockStore()
        inst = V1Instance(Config(cache_size=1 << 10, store=store,
                                 sweep_interval_ms=0,
                                 step_impl="pallas"),
                          mesh=make_mesh(n=2))
        try:
            r = inst.get_rate_limits([sreq()], now_ms=NOW)[0]
            assert r.remaining == 9
            assert store.called["on_change"] == 1
            assert store.items["rt_k1"].remaining == 9
        finally:
            inst.close()

        # a SECOND pallas instance seeds from the persisted row
        store.items["rt_k1"] = CacheItem(
            key="rt_k1", limit=10, duration=60_000, eff_ms=60_000,
            remaining=3, t_ms=NOW, expire_at=NOW + 60_000)
        inst2 = V1Instance(Config(cache_size=1 << 10, store=store,
                                  sweep_interval_ms=0,
                                  step_impl="pallas"),
                           mesh=make_mesh(n=2))
        try:
            r = inst2.get_rate_limits([sreq(hits=0)],
                                      now_ms=NOW + 1000)[0]
            assert r.remaining == 3, "store state not seeded"
        finally:
            inst2.close()


class TestCapacitySafety:
    def test_autogrow_ignored_warns_at_startup(self, caplog,
                                               monkeypatch):
        """VERDICT r4 weak #4 / item 6: flipping GUBER_STEP_IMPL=pallas
        with auto-grow configured must not SILENTLY change capacity
        semantics — the operator gets told at startup."""
        import logging

        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance

        monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
        with caplog.at_level(logging.WARNING,
                             logger="gubernator_tpu.instance"):
            inst = V1Instance(Config(cache_size=1 << 10,
                                     sweep_interval_ms=0,
                                     step_impl="pallas",
                                     cache_autogrow_max=1 << 20),
                              mesh=make_mesh(n=1))
            inst.close()
        assert any("cache_autogrow_max" in r.getMessage()
                   and "bucket_saturation" in r.getMessage()
                   for r in caplog.records)
        # and no warning when auto-grow is off
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="gubernator_tpu.instance"):
            inst = V1Instance(Config(cache_size=1 << 10,
                                     sweep_interval_ms=0,
                                     step_impl="pallas"),
                              mesh=make_mesh(n=1))
            inst.close()
        assert not any("cache_autogrow_max" in r.getMessage()
                       for r in caplog.records)

    def test_bucket_saturation_watermark(self, monkeypatch):
        """The watermark counts FULL buckets (the unservability unit:
        new keys hashing into one err as table_full) and exports as
        gubernator_pallas_bucket_saturation via health_check."""
        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance
        from gubernator_tpu.ops import pallas_step as ps

        monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
        inst = V1Instance(Config(cache_size=1 << 10,
                                 sweep_interval_ms=0,
                                 step_impl="pallas"),
                          mesh=make_mesh(n=1))
        try:
            eng = inst.engine
            nb = eng.cap_local // ps.SLOTS
            full, total = eng.bucket_saturation()
            assert (full, total) == (0, nb)
            # 8 distinct keys engineered into bucket 3 of shard 0
            # (bucket = khash & (nb-1); shard from the top 32 bits = 0)
            keys = (np.arange(1, ps.SLOTS + 1, dtype=np.uint64)
                    * np.uint64(nb)) | np.uint64(3)
            n = len(keys)
            arrays = {"meta": np.zeros(n, np.int32),
                      "limit": np.full(n, 10, np.int64),
                      "burst": np.full(n, 10, np.int64),
                      "remaining": np.full(n, 5, np.int64),
                      "duration": np.full(n, 60_000, np.int64),
                      "eff_ms": np.full(n, 60_000, np.int64),
                      "t_ms": np.full(n, NOW, np.int64),
                      "expire_at": np.full(n, NOW + 60_000, np.int64)}
            assert eng.upsert_rows(keys, arrays) == ps.SLOTS
            full, total = eng.bucket_saturation()
            assert (full, total) == (1, nb)
            inst.health_check()
            assert inst.metrics.bucket_saturation._value.get() == \
                pytest.approx(1 / nb)
        finally:
            inst.close()


class TestInstanceIntegration:
    def test_v1instance_pallas_mode(self, monkeypatch):
        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance
        from gubernator_tpu.parallel.pallas_engine import (
            PallasServingEngine)

        # env has precedence over Config — an inherited override would
        # flip the engine under test
        monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
        inst = V1Instance(Config(cache_size=1 << 10,
                                 sweep_interval_ms=0,
                                 step_impl="pallas"),
                          mesh=make_mesh(n=1))
        try:
            assert isinstance(inst.engine, PallasServingEngine)
            resps = inst.get_rate_limits(
                [req("v1", limit=3) for _ in range(5)], now_ms=NOW)
            assert [int(r.status) for r in resps] == [0, 0, 0, 1, 1]
            assert [r.remaining for r in resps] == [2, 1, 0, 0, 0]
        finally:
            inst.close()

"""PallasServingEngine: the Mosaic kernel as a deployable serving mode.

Engine-protocol parity vs ShardedEngine (the XLA mode) on shared
request streams — decisions, sweep, row ops, snapshot/restore — plus
the domain gate.  Runs the kernel in interpret mode on CPU (same
reference interpreter as test_pallas_step.py)."""
import numpy as np
import pytest

import jax.numpy as jnp

from gubernator_tpu.hashing import hash_request_keys
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.pallas_engine import PallasServingEngine
from gubernator_tpu.types import RateLimitRequest

NOW = 1_765_000_000_000


def req(key, **kw):
    d = dict(hits=1, limit=10, duration=10_000)
    d.update(kw)
    return RateLimitRequest(name="pe", unique_key=key, **d)


@pytest.fixture()
def engines():
    mesh = make_mesh(n=2)
    pe = PallasServingEngine(mesh, capacity_per_shard=1 << 9,
                             batch_per_shard=64)
    xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                       batch_per_shard=64)
    return pe, xe


def both(engines, reqs, now):
    pe, xe = engines
    rp = pe.check_batch(reqs, now)
    rx = xe.check_batch(reqs, now)
    for i, (a, b) in enumerate(zip(rp, rx)):
        assert (int(a.status), a.remaining, a.reset_time, a.limit) == \
            (int(b.status), b.remaining, b.reset_time, b.limit), i
    return rp


class TestServingParity:
    def test_token_flow_and_counters(self, engines):
        pe, xe = engines
        reqs = [req(f"k{i % 6}", hits=2) for i in range(24)]
        both(engines, reqs, NOW)
        both(engines, reqs, NOW + 500)
        # deny region
        both(engines, reqs, NOW + 600)
        assert pe.over_count == xe.over_count
        assert pe.insert_count == xe.insert_count
        # expiry → fresh
        both(engines, reqs, NOW + 30_000)

    def test_leaky_flow(self, engines):
        reqs = [req(f"l{i % 4}", algorithm=1, hits=3, limit=100,
                    burst=100, duration=60_000) for i in range(16)]
        both(engines, reqs, NOW)
        both(engines, reqs, NOW + 2_000)
        both(engines, reqs, NOW + 90_000)

    def test_mixed_algorithms_and_flags(self, engines):
        rng = np.random.default_rng(3)
        reqs = []
        for i in range(48):
            alg = i % 2
            beh = 8 if i % 7 == 0 else (32 if i % 11 == 0 else 0)
            reqs.append(req(f"m{i % 9}", algorithm=alg,
                            hits=int(rng.integers(0, 4)),
                            limit=20, burst=20, behavior=beh))
        both(engines, reqs, NOW)
        both(engines, reqs, NOW + 100)

    def test_out_of_domain_rows_scoped_not_fatal(self, engines):
        """A row outside the kernel's value domain must not fail the
        wave (the dispatcher coalesces independent callers): it comes
        back unservable ('rate limit table full') while every other
        row serves normally — and the device state is untouched by it."""
        pe, _ = engines
        resps = pe.check_batch(
            [req("ok1", limit=5), req("big", limit=1 << 31),
             req("ok2", limit=5)], NOW)
        assert resps[0].error == "" and resps[0].remaining == 4
        assert resps[2].error == "" and resps[2].remaining == 4
        assert "full" in resps[1].error
        # the out-of-domain key left no row behind
        kh = hash_request_keys(["pe"], ["big"])
        found, _ = pe.gather_rows(kh)
        assert not found.any()

    def test_out_of_domain_rows_scoped_pipelined(self, engines):
        """Same scoping through the pipelined launch/sync pair (the
        TPU dispatcher path calls these directly)."""
        from gubernator_tpu.core.batch import pack_requests

        pe, _ = engines
        reqs = [req("p1", limit=5), req("huge", hits=1 << 31),
                req("p2", limit=5)]
        kh = hash_request_keys(["pe"] * 3, ["p1", "huge", "p2"])
        batch, _errs = pack_requests(reqs, NOW, size=3, key_hashes=kh)
        token = pe.launch_packed(batch, kh, NOW)
        st, lim, rem, rst, full = pe.sync_packed(token)
        assert list(full) == [False, True, False]
        assert rem[0] == 4 and rem[2] == 4

    def test_sweep_reclaims_expired(self, engines):
        pe, xe = engines
        reqs = [req(f"s{i}") for i in range(12)]
        both(engines, reqs, NOW)
        pe.sweep(NOW + 60_000)
        xe.sweep(NOW + 60_000)
        assert pe.live_rows == 0
        # the slots actually free again (fresh inserts succeed)
        both(engines, reqs, NOW + 61_000)

    def test_sweep_keeps_live_rows(self, engines):
        pe, _ = engines
        both(engines, [req(f"sl{i}") for i in range(5)], NOW)
        pe.sweep(NOW + 1_000)  # inside the 10s window
        assert pe.live_rows == 5


class TestRowOps:
    def test_gather_upsert_remove_roundtrip(self, engines):
        pe, xe = engines
        reqs = [req(f"r{i}", hits=4) for i in range(8)]
        both(engines, reqs, NOW)
        kh = hash_request_keys(["pe"] * 8,
                               [f"r{i}" for i in range(8)])
        fp, cp = pe.gather_rows(kh)
        fx, cx = xe.gather_rows(kh)
        assert fp.all() and fx.all()
        for f in ("meta", "limit", "remaining", "t_ms", "expire_at",
                  "duration", "eff_ms"):
            assert (cp[f] == cx[f]).all(), f
        # upsert modified state into BOTH engines → still in lockstep
        cp["remaining"] = cp["remaining"] + 3
        assert pe.upsert_rows(kh, cp) == 8
        assert xe.upsert_rows(kh, cp) == 8
        both(engines, [req(f"r{i}", hits=0) for i in range(8)], NOW + 10)
        # remove → keys re-insert fresh
        assert pe.remove_rows(kh[:4]) == 4
        assert xe.remove_rows(kh[:4]) == 4
        both(engines, reqs, NOW + 20)

    def test_gather_missing_keys(self, engines):
        pe, _ = engines
        kh = hash_request_keys(["pe"], ["never-seen"])
        found, _ = pe.gather_rows(kh)
        assert not found.any()


class TestSnapshotRestore:
    def test_snapshot_matches_xla_columns(self, engines):
        pe, xe = engines
        reqs = [req(f"ss{i}", hits=2) for i in range(10)]
        both(engines, reqs, NOW)
        sp = pe.snapshot()
        sx = xe.snapshot()
        op = np.argsort(sp["key"])
        ox = np.argsort(sx["key"])
        assert (sp["key"][op] == sx["key"][ox]).all()
        for f in ("meta", "limit", "remaining", "t_ms", "expire_at"):
            assert (sp[f][op] == sx[f][ox]).all(), f

    def test_restore_roundtrip_across_engine_kinds(self):
        """An XLA-engine snapshot restores into a pallas engine (and
        back): checkpoint/resume is layout-independent."""
        xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                           batch_per_shard=64)
        reqs = [req(f"x{i}", hits=3) for i in range(9)]
        xe.check_batch(reqs, NOW)
        snap = xe.snapshot()

        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        assert pe.restore(snap) == 9
        # restored counters serve identically
        q = [req(f"x{i}", hits=0) for i in range(9)]
        rp = pe.check_batch(q, NOW + 5)
        rx = xe.check_batch(q, NOW + 5)
        for a, b in zip(rp, rx):
            assert (int(a.status), a.remaining) == \
                (int(b.status), b.remaining)
        # and back: pallas snapshot → fresh XLA engine
        xe2 = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                            batch_per_shard=64)
        assert xe2.restore(pe.snapshot()) == 9
        rx2 = xe2.check_batch(q, NOW + 6)
        rp2 = pe.check_batch(q, NOW + 6)
        for a, b in zip(rx2, rp2):
            assert (int(a.status), a.remaining) == \
                (int(b.status), b.remaining)

    def test_restore_drops_out_of_domain_rows(self):
        xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                           batch_per_shard=64)
        xe.check_batch([req("huge", limit=1 << 40),
                        req("ok", limit=5)], NOW)
        snap = xe.snapshot()
        pe = PallasServingEngine(make_mesh(n=2),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        assert pe.restore(snap) == 1
        assert pe.dropped_rows == 1


class TestStoreIntegration:
    def test_store_write_and_read_through_pallas_mode(self, monkeypatch):
        """The Store subsystem (persistence hooks) runs unchanged over
        the bucket layout: write-through sees mutations via the bucket
        row ops; a fresh pallas-mode instance read-through-seeds from
        persisted state."""
        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance
        from gubernator_tpu.store import CacheItem, MockStore
        from gubernator_tpu.types import RateLimitRequest

        monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)

        def sreq(**kw):
            d = dict(hits=1, limit=10, duration=60_000)
            d.update(kw)
            return RateLimitRequest(name="rt", unique_key="k1", **d)

        store = MockStore()
        inst = V1Instance(Config(cache_size=1 << 10, store=store,
                                 sweep_interval_ms=0,
                                 step_impl="pallas"),
                          mesh=make_mesh(n=2))
        try:
            r = inst.get_rate_limits([sreq()], now_ms=NOW)[0]
            assert r.remaining == 9
            assert store.called["on_change"] == 1
            assert store.items["rt_k1"].remaining == 9
        finally:
            inst.close()

        # a SECOND pallas instance seeds from the persisted row
        store.items["rt_k1"] = CacheItem(
            key="rt_k1", limit=10, duration=60_000, eff_ms=60_000,
            remaining=3, t_ms=NOW, expire_at=NOW + 60_000)
        inst2 = V1Instance(Config(cache_size=1 << 10, store=store,
                                  sweep_interval_ms=0,
                                  step_impl="pallas"),
                           mesh=make_mesh(n=2))
        try:
            r = inst2.get_rate_limits([sreq(hits=0)],
                                      now_ms=NOW + 1000)[0]
            assert r.remaining == 3, "store state not seeded"
        finally:
            inst2.close()


class TestInstanceIntegration:
    def test_v1instance_pallas_mode(self, monkeypatch):
        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance
        from gubernator_tpu.parallel.pallas_engine import (
            PallasServingEngine)

        # env has precedence over Config — an inherited override would
        # flip the engine under test
        monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
        inst = V1Instance(Config(cache_size=1 << 10,
                                 sweep_interval_ms=0,
                                 step_impl="pallas"),
                          mesh=make_mesh(n=1))
        try:
            assert isinstance(inst.engine, PallasServingEngine)
            resps = inst.get_rate_limits(
                [req("v1", limit=3) for _ in range(5)], now_ms=NOW)
            assert [int(r.status) for r in resps] == [0, 0, 0, 1, 1]
            assert [r.remaining for r in resps] == [2, 1, 0, 0, 0]
        finally:
            inst.close()

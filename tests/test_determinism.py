"""Determinism: the race-detector analog (SURVEY.md §5.2).

The reference keeps `go test -race` clean via its locking design; the
TPU design's equivalent guarantee is *determinism* — the same request
stream (same now_ms values) must produce bit-identical decisions and
table state on every run, on any shard count, with any batch
composition, including under concurrent client threads hitting one
instance."""
import threading

import numpy as np
import pytest

from gubernator_tpu import Algorithm, Behavior, RateLimitRequest
from gubernator_tpu.parallel import ShardedEngine, make_mesh

NOW = 1_761_000_000_000


def _stream(seed, n_batches=4, batch=96, n_keys=40):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        reqs = []
        for _ in range(batch):
            k = int(rng.integers(0, n_keys))
            reqs.append(RateLimitRequest(
                name="det", unique_key=f"k{k}",
                hits=int(rng.integers(0, 4)),
                limit=int(rng.integers(1, 20)),
                duration=int(rng.integers(1000, 100_000)),
                algorithm=Algorithm.LEAKY_BUCKET if rng.integers(2)
                else Algorithm.TOKEN_BUCKET,
                behavior=Behavior.RESET_REMAINING if rng.integers(13) == 0
                else Behavior.BATCHING))
        out.append((reqs, NOW + b * 3_000))
    return out


def _run(mesh_n, stream, engine_cls=ShardedEngine):
    eng = engine_cls(make_mesh(n=mesh_n), capacity_per_shard=1 << 10,
                     batch_per_shard=64)
    results = []
    for reqs, now in stream:
        results.extend((int(r.status), r.remaining, r.reset_time, r.limit)
                       for r in eng.check_batch(reqs, now))
    return results, eng


def test_identical_streams_identical_decisions():
    s = _stream(11)
    r1, e1 = _run(4, s)
    r2, e2 = _run(4, s)
    assert r1 == r2
    # table state must match bit-for-bit too
    for f in e1.state._fields:
        assert (np.asarray(getattr(e1.state, f))
                == np.asarray(getattr(e2.state, f))).all(), f


def test_shard_count_does_not_change_decisions():
    """1-shard vs 4-shard engines agree on every decision (the layout is
    an implementation detail, not a semantic)."""
    s = _stream(12)
    r1, _ = _run(1, s)
    r4, _ = _run(4, s)
    assert r1 == r4


def test_pallas_mode_is_deterministic_and_layout_independent():
    """The same contract for step_impl=pallas: identical streams →
    bit-identical decisions AND table words; and the kernel engine
    agrees with the XLA engine decision-for-decision on the stream
    (the serving mode is a layout choice, not a semantic).  Domain
    note: _stream's limits/durations all sit inside the kernel's
    value bounds, so no row is domain-dropped here."""
    from gubernator_tpu.parallel.pallas_engine import PallasServingEngine

    s = _stream(13)
    r1, e1 = _run(2, s, engine_cls=PallasServingEngine)
    r2, e2 = _run(2, s, engine_cls=PallasServingEngine)
    assert r1 == r2
    assert (np.asarray(e1.state) == np.asarray(e2.state)).all()
    rx, _ = _run(2, s)
    assert r1 == rx


def test_concurrent_clients_conserve_hits():
    """Threaded access to one instance: total admitted hits must equal
    the bucket capacity exactly — no lost or double-counted updates."""
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance

    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      mesh=make_mesh(n=2))
    admitted = []
    lock = threading.Lock()

    def worker(w):
        got = 0
        for _ in range(30):
            r = inst.get_rate_limits(
                [RateLimitRequest(name="conserve", unique_key="one",
                                  hits=1, limit=100, duration=600_000)],
                now_ms=NOW)[0]
            if int(r.status) == 0:
                got += 1
        with lock:
            admitted.append(got)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 8×30 = 240 attempts against capacity 100: exactly 100 admitted
    assert sum(admitted) == 100
    inst.close()


def test_concurrent_wire_clients_conserve_hits():
    """The C++ wire lane under threaded load: coalesced packed jobs in
    the dispatcher must conserve hits exactly like the object path."""
    import pytest

    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance, _wire_native
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.wire import req_to_pb

    if _wire_native is None:  # pragma: no cover
        pytest.skip("native extension not built")
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      mesh=make_mesh(n=2))
    m = pb.GetRateLimitsReq()
    m.requests.extend(req_to_pb(RateLimitRequest(
        name="conserve", unique_key="wire", hits=1, limit=100,
        duration=600_000)) for _ in range(5))
    data = m.SerializeToString()
    admitted = []
    lock = threading.Lock()

    def worker(w):
        got = 0
        for _ in range(10):
            out = pb.GetRateLimitsResp.FromString(
                inst.get_rate_limits_wire(data, now_ms=NOW))
            got += sum(1 for r in out.responses if int(r.status) == 0)
        with lock:
            admitted.append(got)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 6×10×5 = 300 attempts against capacity 100: exactly 100 admitted
    assert sum(admitted) == 100
    inst.close()

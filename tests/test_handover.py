"""Stateful re-sharding (beyond-reference, opt-in): on membership
change, rows whose ring owner moved are handed to the new owner over
the peer wire instead of resetting (the reference loses re-homed state
— SURVEY.md §5.3; ARCHITECTURE.md §6)."""
import time

import pytest

from gubernator_tpu.client import Client
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.netutil import free_port
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.types import RateLimitRequest

N_KEYS = 40


def mk_daemon(mesh, handover=True):
    return spawn_daemon(DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address="",
        cache_size=1 << 10,
        handover_on_reshard=handover), mesh=mesh)


def req(i, hits=1):
    return RateLimitRequest(name="ho", unique_key=f"k{i}", hits=hits,
                            limit=10, duration=600_000)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(n=2)


def _remaining_via(daemon, i):
    with Client(f"127.0.0.1:{daemon.grpc_port}") as c:
        return c.get_rate_limits([req(i, hits=0)])[0].remaining


def test_join_hands_over_moved_rows(mesh):
    d1 = mk_daemon(mesh)
    d2 = None
    try:
        with Client(f"127.0.0.1:{d1.grpc_port}") as c:
            rs = c.get_rate_limits([req(i, hits=3) for i in range(N_KEYS)])
            assert all(r.error == "" and int(r.status) == 0 for r in rs)
            # hits=3 per key → remaining 7 everywhere
            assert {r.remaining for r in rs} == {7}
        d2 = mk_daemon(mesh)
        infos = [d1.peer_info(), d2.peer_info()]
        d1.set_peers(infos)
        d2.set_peers(infos)
        # some keys now belong to d2; without handover they'd read 10
        deadline = time.time() + 30
        while time.time() < deadline:
            vals = [_remaining_via(d1, i) for i in range(N_KEYS)]
            if all(v == 7 for v in vals):
                break
            time.sleep(0.2)
        assert all(v == 7 for v in vals), vals
        # and d2 genuinely holds some of them now (handover, not
        # forwarding trickery): its own engine answers for moved keys
        from gubernator_tpu.core.table import occupancy

        assert int(occupancy(d2.instance.engine.state)) > 0
        # d1 normally drops what it handed over; under CI load a
        # delivery can exceed its client deadline while the server
        # still applied it, in which case rows legitimately stay on d1
        # (best-effort contract) — poll briefly, don't flake on it
        deadline = time.time() + 30
        while (time.time() < deadline
               and int(occupancy(d1.instance.engine.state)) > N_KEYS):
            time.sleep(0.5)
        # correctness (remaining preserved everywhere) was asserted
        # above regardless of whether the local drop completed
    finally:
        d1.close()
        if d2 is not None:
            d2.close()


def test_join_without_handover_resets_moved_rows(mesh):
    """The reference behavior (and our default): re-homed keys reset."""
    d1 = mk_daemon(mesh, handover=False)
    d2 = None
    try:
        with Client(f"127.0.0.1:{d1.grpc_port}") as c:
            c.get_rate_limits([req(i, hits=3) for i in range(N_KEYS)])
        d2 = mk_daemon(mesh, handover=False)
        infos = [d1.peer_info(), d2.peer_info()]
        d1.set_peers(infos)
        d2.set_peers(infos)
        vals = [_remaining_via(d1, i) for i in range(N_KEYS)]
        # moved keys read fresh (10), kept keys read 7 — both present
        assert 10 in vals and 7 in vals, vals
    finally:
        d1.close()
        if d2 is not None:
            d2.close()


def test_handover_preserves_30day_leaky_fixed_point(mesh):
    """Cross-feature: int64-duration leaky rows survive a handover
    losslessly.  A 30-day leaky bucket's remaining is td fixed point
    (remaining × eff, eff ≈ 2.59e9 ms > the old 2^31-1 clamp); the
    transfer sends the RAW value with eff_ms, so the new owner must
    answer with the exact same floor remaining."""
    from gubernator_tpu.types import Algorithm

    MONTH = 30 * 86_400_000

    def lreq(i, hits=1):
        return RateLimitRequest(
            name="ho64", unique_key=f"m{i}", hits=hits, limit=30,
            duration=MONTH, algorithm=Algorithm.LEAKY_BUCKET, burst=12)

    d1 = mk_daemon(mesh)
    d2 = None
    try:
        with Client(f"127.0.0.1:{d1.grpc_port}") as c:
            rs = c.get_rate_limits([lreq(i, hits=5) for i in range(N_KEYS)])
            assert all(r.error == "" for r in rs)
            # burst 12, 5 consumed → remaining floor 7 (leak over test
            # runtime is ~1 token/day: invisible)
            assert {r.remaining for r in rs} == {7}, \
                {r.remaining for r in rs}
        d2 = mk_daemon(mesh)
        infos = [d1.peer_info(), d2.peer_info()]
        d1.set_peers(infos)
        d2.set_peers(infos)
        deadline = time.time() + 30
        vals = []
        while time.time() < deadline:
            with Client(f"127.0.0.1:{d1.grpc_port}") as c:
                vals = [c.get_rate_limits([lreq(i, hits=0)])[0].remaining
                        for i in range(N_KEYS)]
            if all(v == 7 for v in vals):
                break
            time.sleep(0.2)
        assert all(v == 7 for v in vals), vals
    finally:
        d1.close()
        if d2 is not None:
            d2.close()


def test_gossip_join_triggers_handover(mesh):
    """End-to-end elasticity: a second daemon joins via GOSSIP discovery
    (no manual SetPeers), membership propagates over UDP heartbeats,
    both daemons rebuild their rings, and — with handover enabled — the
    rows whose ring owner moved arrive at the joiner with their
    consumption intact.  This is the reference's memberlist-driven
    SetPeers flow (memberlist.go › MemberListPool → SetPeers) composed
    with the beyond-reference stateful re-shard."""
    def mk_gossip_daemon(seeds):
        return spawn_daemon(DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{free_port()}",
            http_listen_address="",
            cache_size=1 << 10,
            handover_on_reshard=True,
            peer_discovery_type="member-list",
            memberlist_known_hosts=seeds), mesh=mesh)

    d1 = mk_gossip_daemon([])
    d2 = None
    try:
        with Client(f"127.0.0.1:{d1.grpc_port}") as c:
            rs = c.get_rate_limits([req(i, hits=3) for i in range(N_KEYS)])
            assert all(r.error == "" for r in rs)
            assert {r.remaining for r in rs} == {7}
        # join via gossip only: seed = d1's gossip bind (grpc port + 1)
        d2 = mk_gossip_daemon([f"127.0.0.1:{d1.grpc_port + 1}"])
        deadline = time.time() + 40
        vals = []
        while time.time() < deadline:
            # membership must converge to 2 on both daemons...
            if (len(d1.instance.peers()) == 2
                    and len(d2.instance.peers()) == 2):
                vals = [_remaining_via(d1, i) for i in range(N_KEYS)]
                # ...and every key must still read 7 (handover, not
                # reset) no matter which daemon now owns it
                if all(v == 7 for v in vals):
                    break
            time.sleep(0.3)
        assert len(d1.instance.peers()) == 2, "gossip never converged"
        assert all(v == 7 for v in vals), vals
        # the joiner genuinely owns some rows now
        from gubernator_tpu.core.table import occupancy

        assert int(occupancy(d2.instance.engine.state)) > 0
    finally:
        d1.close()
        if d2 is not None:
            d2.close()

"""TPU-lowering regression gate, no TPU required.

tools/lower_check.py cross-lowers all three decision-step modes for the
TPU target on the CPU backend (``trace().lower(lowering_platforms=
("tpu",))`` runs the full Pallas→Mosaic pipeline client-side).  Three
kernel bugs that only surfaced on real hardware on 2026-08-01 — the
Mosaic block-shape rule, rank-1 reduction proxies emitting float64
converts under global x64, and an unsupported float cumsum — are all
caught by this check; this test keeps them caught.

Runs in a subprocess: the check needs its own interpreter (platform
config + x64 are set at import time, and conftest's 8-device CPU setup
must not leak in).
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_all_step_modes_lower_for_tpu():
    # minimal env: conftest mutates XLA_FLAGS/JAX_* at import time and
    # forwarding them would make this gate test a different config than
    # a standalone `python tools/lower_check.py`
    env = {k: v for k, v in os.environ.items()
           if not (k.startswith(("JAX_", "XLA_")) or k.startswith("GUBER_"))}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lower_check.py")],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, f"lowering check failed:\n{r.stdout}\n{r.stderr}"
    for name in ("pallas_step", "xla_step", "xla_step_donated"):
        assert f"{name}: lowers for TPU" in r.stdout, r.stdout

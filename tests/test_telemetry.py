"""Wave telemetry + flight recorder + stall watchdog (ISSUE 1).

The watchdog tests inject a fake clock and a gated engine — no real
sleeps: a wave "ages" only when the test advances the clock, and
``_watchdog_poll()`` is driven directly."""
import threading

import pytest

from gubernator_tpu.dispatcher import Dispatcher
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.telemetry import FlightRecorder, exc_text
from gubernator_tpu.types import RateLimitRequest

NOW = 1_780_000_000_000


def req(key, **kw):
    d = dict(hits=1, limit=1000, duration=600_000)
    d.update(kw)
    return RateLimitRequest(name="tel", unique_key=key, **d)


# ---- exc_text -----------------------------------------------------------


def test_exc_text_never_empty():
    # the round-5 bug: str(TimeoutError()) == "" made rows undiagnosable
    assert str(TimeoutError()) == ""
    assert exc_text(TimeoutError()) == "TimeoutError()"
    assert exc_text(ValueError("boom")) == "boom"


# ---- flight recorder ----------------------------------------------------


def test_recorder_ring_bounds_and_ordering():
    r = FlightRecorder(capacity=8)
    for i in range(20):
        r.record("tick", i=i)
    evs = r.events()
    assert len(evs) == 8 == len(r)
    # oldest events fell off; the survivors are the newest, in order
    assert [e["i"] for e in evs] == list(range(12, 20))
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and seqs[-1] == 20
    assert [e["i"] for e in r.events(limit=3)] == [17, 18, 19]


def test_recorder_events_are_json_safe_and_error_nonempty():
    import json

    r = FlightRecorder()
    r.record("weird", obj=object(), n=3, flag=True, none=None)
    r.record_error("oops", TimeoutError())
    evs = r.events()
    json.dumps(evs)  # must not raise
    assert evs[0]["obj"].startswith("<object object")
    assert evs[1]["error"] == "TimeoutError()"  # never ""


def test_recorder_captures_active_trace_id():
    from gubernator_tpu.tracing import request_context

    r = FlightRecorder()
    tid = "ab" * 16
    with request_context(f"00-{tid}-{'cd' * 8}-01"):
        r.record("in_ctx")
    r.record("out_ctx")
    evs = r.events()
    assert evs[0]["trace"] == tid
    assert evs[1]["trace"] is None


def test_recorder_capacity_validation():
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_recorder_server_side_kind_and_since_seq_filters():
    """ISSUE 4 satellite: events() filters by kind and by seq so the
    daemon can serve ?kind= / ?since_seq= without shipping the ring."""
    r = FlightRecorder()
    for i in range(6):
        r.record("even" if i % 2 == 0 else "odd", i=i)
    assert [e["i"] for e in r.events(kind="odd")] == [1, 3, 5]
    assert [e["i"] for e in r.events(since_seq=4)] == [4, 5]
    assert [e["i"] for e in r.events(kind="even", since_seq=2)] == [2, 4]
    assert [e["i"] for e in r.events(kind="even", limit=1)] == [4]
    assert r.events(kind="nope") == []


def test_recorder_keeps_dict_fields_queryable():
    """The wave_completed `phases` block must survive as a JSON object,
    not a repr string (one level deep; nested values still coerce)."""
    import json

    r = FlightRecorder()
    r.record("wave_completed", phases={"pack": 0.5, "device": 2.0},
             weird={"obj": object()})
    ev = r.events()[0]
    json.dumps(ev)
    assert ev["phases"] == {"pack": 0.5, "device": 2.0}
    assert ev["weird"]["obj"].startswith("<object object")


# ---- dispatcher wave metrics --------------------------------------------


@pytest.fixture()
def engine():
    # the pure-Python referee engine: wave telemetry is engine-agnostic
    # and must be testable without the jax sharded stack
    from gubernator_tpu.oracle import OracleEngine

    return OracleEngine()


def test_wave_histograms_observed_after_dispatch(engine):
    m, rec = Metrics(), FlightRecorder()
    d = Dispatcher(engine, metrics=m, recorder=rec)
    try:
        r = d.check_batch([req("a"), req("b")], NOW)
        assert len(r) == 2
    finally:
        d.close()
    text = m.render().decode()
    assert "gubernator_dispatcher_wave_size_count 1.0" in text
    assert "gubernator_dispatcher_wave_duration_count 1.0" in text
    # idle dispatcher → inline wave: in-flight returned to 0, no stall
    assert "gubernator_dispatcher_waves_in_flight 0.0" in text
    assert "gubernator_dispatcher_stalled 0.0" in text
    assert "gubernator_dispatcher_first_wave_seconds" in text
    kinds = [e["kind"] for e in rec.events()]
    assert kinds == ["wave_launched", "wave_completed", "first_wave"]
    stats = d.debug_stats()
    assert stats["waves"] == 1 and stats["timeouts"] == 0
    assert stats["first_wave_s"] is not None


def test_queue_wait_observed_for_queued_wave(engine):
    m = Metrics()
    d = Dispatcher(engine, metrics=m)
    # force the queue path: with the inline mutex held, callers submit
    # jobs and the worker coalesces them into one wave
    d._inline_mu.acquire()
    try:
        threads = [threading.Thread(
            target=lambda i=i: d.check_batch([req(f"q{i}")], NOW))
            for i in range(3)]
        for t in threads:
            t.start()
    finally:
        d._inline_mu.release()
    for t in threads:
        t.join(timeout=60)
    d.close()
    text = m.render().decode()
    # every queued job contributed one queue-wait sample
    import re

    count = float(re.search(
        r"gubernator_dispatcher_queue_wait_count (\S+)", text).group(1))
    assert count == 3.0
    snap = d.telemetry_snapshot()
    assert snap["queue_wait_p50_ms"] is not None
    assert snap["wave_size_p50"] >= 1


def test_engine_error_recorded_as_wave_error(engine):
    rec = FlightRecorder()
    d = Dispatcher(engine, recorder=rec)

    def boom(reqs, now):
        raise RuntimeError("device on fire")

    d.engine = type("E", (), {"check_batch": staticmethod(boom)})()
    try:
        with pytest.raises(RuntimeError, match="device on fire"):
            d.check_batch([req("x")], NOW)
    finally:
        d.close()
    errs = [e for e in rec.events() if e["kind"] == "wave_error"]
    assert errs and errs[0]["error"] == "device on fire"


# ---- per-phase latency attribution (ISSUE 4) ----------------------------


def _phase_sums(text):
    import re

    out = {}
    for ph, v in re.findall(
            r'gubernator_phase_duration_sum\{phase="(\w+)"\} (\S+)',
            text):
        out[ph] = float(v)
    return out


def test_phase_histograms_partition_wave_duration(engine):
    """ISSUE 4 acceptance: pack + device + resolve sum to the existing
    wave_duration (same clock, marks stamp segment ends), over inline
    AND queued waves."""
    from gubernator_tpu.analytics import KeyAnalytics

    m, rec = Metrics(), FlightRecorder()
    ka = KeyAnalytics(metrics=m)
    d = Dispatcher(engine, metrics=m, recorder=rec, analytics=ka)
    try:
        for i in range(4):  # inline waves
            d.check_batch([req(f"p{i}")], NOW + i)
        # queued path: coalesced wave with queue-wait samples
        d._inline_mu.acquire()
        try:
            threads = [threading.Thread(
                target=lambda i=i: d.check_batch([req(f"pq{i}")], NOW))
                for i in range(3)]
            for t in threads:
                t.start()
        finally:
            d._inline_mu.release()
        for t in threads:
            t.join(timeout=60)
    finally:
        d.close()
        ka.close()
    import re

    text = m.render().decode()
    sums = _phase_sums(text)
    wave_sum = float(re.search(
        r"gubernator_dispatcher_wave_duration_sum (\S+)", text).group(1))
    in_wave = sums["pack"] + sums["device"] + sums["resolve"]
    assert in_wave == pytest.approx(wave_sum, rel=1e-6, abs=1e-9)
    # queue_wait mirrors the dispatcher's own histogram sample count
    qw = float(re.search(
        r'gubernator_phase_duration_count\{phase="queue_wait"\} (\S+)',
        text).group(1))
    qw_disp = float(re.search(
        r"gubernator_dispatcher_queue_wait_count (\S+)", text).group(1))
    assert qw == qw_disp == 3.0
    # the per-wave breakdown rode the flight-recorder events and sums
    # to each wave's duration
    for ev in rec.events(kind="wave_completed"):
        ph = ev["phases"]
        assert set(ph) == {"pack", "device", "resolve"}
        assert sum(ph.values()) == pytest.approx(ev["duration_ms"],
                                                 abs=0.002)


def test_phase_histogram_without_analytics_attached(engine):
    """Phase attribution must not require the analytics subsystem: a
    dispatcher with metrics but analytics=None still feeds the
    histograms (and nothing crashes on the tap paths)."""
    m = Metrics()
    d = Dispatcher(engine, metrics=m)
    try:
        d.check_batch([req("na")], NOW)
    finally:
        d.close()
    sums = _phase_sums(m.render().decode())
    assert set(sums) >= {"pack", "device", "resolve"}


def test_wave_error_still_recorded_with_marks(engine):
    """An engine raise mid-wave (after the pack mark) must not break
    phase segmentation on the error path."""
    from gubernator_tpu.analytics import KeyAnalytics

    ka = KeyAnalytics(metrics=None)
    rec = FlightRecorder()
    d = Dispatcher(engine, recorder=rec, analytics=ka)

    def boom(reqs, now):
        raise RuntimeError("mid-wave")

    d.engine = type("E", (), {"check_batch": staticmethod(boom)})()
    try:
        with pytest.raises(RuntimeError, match="mid-wave"):
            d.check_batch([req("x")], NOW)
    finally:
        d.close()
        ka.close()
    errs = rec.events(kind="wave_error")
    assert errs and errs[0]["error"] == "mid-wave"


# ---- stall watchdog (fake clock, no real sleeps) ------------------------


class FakeClock:
    def __init__(self, t0=1000.0):
        self.t = t0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


class GatedEngine:
    """check_batch blocks until released — the injected slow engine."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()

    def check_batch(self, reqs, now):
        self.entered.set()
        assert self.release.wait(timeout=60)
        from gubernator_tpu.types import RateLimitResponse

        return [RateLimitResponse() for _ in reqs]


def test_watchdog_flags_stall_and_recovers(monkeypatch):
    # threshold 0 → no background watchdog thread: the test owns every
    # poll, so the flag/no-reflag assertions are race-free by design
    monkeypatch.setenv("GUBER_STALL_THRESHOLD_S", "0")
    clock = FakeClock()
    eng = GatedEngine()
    m, rec = Metrics(), FlightRecorder()
    d = Dispatcher(eng, metrics=m, recorder=rec, clock=clock)
    d._stall_threshold_s = 30.0  # re-arm for manual polling
    caller = threading.Thread(target=lambda: d.check_batch([req("s")],
                                                           NOW))
    caller.start()
    assert eng.entered.wait(timeout=30)  # wave is in flight (inline)
    try:
        # below threshold: no stall
        clock.advance(29.0)
        assert d._watchdog_poll() is False
        assert d.debug_stats()["stalled"] is False
        # past threshold: flagged exactly once
        clock.advance(2.0)
        assert d._watchdog_poll() is True
        assert d._watchdog_poll() is False  # no re-flag
        text = m.render().decode()
        assert "gubernator_dispatcher_stalled 1.0" in text
        assert "gubernator_dispatcher_stall_events_total 1.0" in text
        stats = d.debug_stats()
        assert stats["stalled"] is True
        assert stats["oldest_wave_age_s"] >= 31.0
        stall = [e for e in rec.events() if e["kind"] == "wave_stalled"]
        assert len(stall) == 1
        assert "stall threshold" in stall[0]["error"]
        assert stall[0]["age_s"] >= 31.0
    finally:
        eng.release.set()
        caller.join(timeout=60)
    # wave completed → gauge clears (wave_end path, no poll needed)
    assert "gubernator_dispatcher_stalled 0.0" in m.render().decode()
    assert d.debug_stats()["stalled"] is False
    assert d.debug_stats()["stall_events"] == 1
    d.close()


def test_watchdog_threshold_env_override_and_disable(engine, monkeypatch):
    monkeypatch.setenv("GUBER_STALL_THRESHOLD_S", "5")
    d = Dispatcher(engine)
    assert d._stall_threshold_s == 5.0 and d._watchdog is not None
    d.close()
    monkeypatch.setenv("GUBER_STALL_THRESHOLD_S", "0")
    d = Dispatcher(engine)
    assert d._watchdog is None  # disabled
    d.close()
    monkeypatch.delenv("GUBER_STALL_THRESHOLD_S")
    monkeypatch.setenv("GUBER_RESULT_TIMEOUT_S", "40")
    d = Dispatcher(engine)
    # default scales down with a tightened caller timeout (40/4)
    assert d._stall_threshold_s == pytest.approx(10.0)
    d.close()


# ---- caller-timeout diagnosis -------------------------------------------


def test_timeout_error_is_diagnosed_and_counted(monkeypatch):
    from concurrent.futures import TimeoutError as FuturesTimeout

    monkeypatch.setenv("GUBER_RESULT_TIMEOUT_S", "0.2")
    eng = GatedEngine()
    m, rec = Metrics(), FlightRecorder()
    d = Dispatcher(eng, metrics=m, recorder=rec)
    # force the queue path so the caller waits on the future
    d._inline_mu.acquire()
    try:
        with pytest.raises(FuturesTimeout) as ei:
            d.check_batch([req("t")], NOW)
    finally:
        d._inline_mu.release()
        eng.release.set()
    msg = str(ei.value)
    assert msg, "timeout error must never str() empty"
    assert "timed out after" in msg and "queue_depth=" in msg
    assert "GUBER_RESULT_TIMEOUT_S" in msg
    assert d.debug_stats()["timeouts"] == 1
    assert "gubernator_dispatcher_wave_timeouts_total 1.0" \
        in m.render().decode()
    tmo = [e for e in rec.events() if e["kind"] == "wave_timeout"]
    assert tmo and tmo[0]["error"]
    d.close()

"""Config layer tests (reference: config_test.go analog)."""
import pytest

from gubernator_tpu.config import (
    BehaviorConfig,
    DaemonConfig,
    load_conf_file,
    parse_duration_ms,
    parse_peer_list,
    setup_daemon_config,
)


def test_parse_duration_ms():
    assert parse_duration_ms("500ms") == 500
    assert parse_duration_ms("30s") == 30_000
    assert parse_duration_ms("1m30s") == 90_000
    assert parse_duration_ms("2h") == 7_200_000
    assert parse_duration_ms("1.5s") == 1500
    assert parse_duration_ms("100us") == 0  # sub-ms floors
    assert parse_duration_ms(250) == 250
    assert parse_duration_ms("250") == 250
    assert parse_duration_ms("-5s") == -5000
    with pytest.raises(ValueError):
        parse_duration_ms("5 parsecs")
    with pytest.raises(ValueError):
        parse_duration_ms("1s2")


def test_defaults():
    d = setup_daemon_config(env={})
    assert d.grpc_listen_address == "localhost:1051"
    assert d.http_listen_address == "localhost:1050"
    assert d.behaviors.batch_limit == 1000
    assert d.peer_discovery_type == "none"
    assert d.tls is None


def test_env_overrides():
    d = setup_daemon_config(env={
        "GUBER_GRPC_ADDRESS": "0.0.0.0:9990",
        "GUBER_CACHE_SIZE": "1048576",
        "GUBER_BATCH_TIMEOUT": "50ms",
        "GUBER_GLOBAL_SYNC_WAIT": "1s",
        "GUBER_PEERS": "a:1051, b:1051@dc2",
        "GUBER_DATA_CENTER": "dc1",
    })
    assert d.grpc_listen_address == "0.0.0.0:9990"
    assert d.cache_size == 1 << 20
    assert d.behaviors.batch_timeout_ms == 50
    assert d.behaviors.global_sync_wait_ms == 1000
    assert d.peer_discovery_type == "static"
    assert d.static_peers == ["a:1051", "b:1051@dc2"]
    peers = parse_peer_list(d.static_peers, d.data_center)
    assert peers[0].grpc_address == "a:1051"
    assert peers[0].datacenter == "dc1"
    assert peers[1].datacenter == "dc2"


def test_conf_file(tmp_path):
    p = tmp_path / "gubernator.conf"
    p.write_text(
        "# example.conf analog\n"
        "\n"
        "GUBER_GRPC_ADDRESS = 127.0.0.1:7777\n"
        "GUBER_BATCH_LIMIT = 500\n"
    )
    d = setup_daemon_config(conf_file=str(p))
    assert d.grpc_listen_address == "127.0.0.1:7777"
    assert d.behaviors.batch_limit == 500


def test_conf_file_invalid(tmp_path):
    p = tmp_path / "bad.conf"
    p.write_text("not a kv line\n")
    with pytest.raises(ValueError):
        load_conf_file(str(p))


def test_tls_from_env():
    d = setup_daemon_config(env={"GUBER_TLS_AUTO": "true"})
    assert d.tls is not None and d.tls.auto_tls
    d = setup_daemon_config(env={
        "GUBER_TLS_CERT": "/c.pem", "GUBER_TLS_KEY": "/k.pem",
        "GUBER_TLS_CLIENT_AUTH": "verify"})
    assert d.tls.cert_file == "/c.pem"
    assert d.tls.client_auth == "verify"


def test_instance_config_normalizes():
    d = DaemonConfig(cache_size=50_000)
    cfg = d.instance_config()
    assert cfg.cache_size == 1 << 16  # rounded up to power of two
    assert cfg.behaviors is d.behaviors

"""Mesh-resident GLOBAL (ISSUE 7): collective hit reconciliation.

8-device CPU dryruns of the `GUBER_GLOBAL_MODE=mesh` backend
(parallel/meshglobal.py + the GlobalManager mesh tick): exact hit
conservation across shards (psum of the per-shard accumulators ==
injected hits), replica convergence through the all-reduce fold,
measured coherence staleness within the configured reconcile interval,
bit-identical decisions vs. the gRPC GLOBAL path on the same seeded
traffic, zero gRPC peer RPCs, and the chaos/degraded-fallback story
(collective faultpoints armed, nothing lost)."""
import time

import numpy as np
import pytest

from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.hashing import hash_key
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import Behavior, RateLimitRequest, Status

NOW = 1_781_000_000_000
SYNC_MS = 100


def ser(reqs):
    m = pb.GetRateLimitsReq()
    for r in reqs:
        q = m.requests.add()
        q.name, q.unique_key = r.name, r.unique_key
        q.hits, q.limit, q.duration = r.hits, r.limit, r.duration
        q.behavior = int(r.behavior)
        q.algorithm = int(r.algorithm)
    return m.SerializeToString()


def greq(key, hits=1, name="mg", **kw):
    d = dict(limit=100_000, duration=600_000, behavior=Behavior.GLOBAL)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, hits=hits, **d)


def mesh_instance(monkeypatch, n=8, **cfg):
    monkeypatch.setenv("GUBER_MESH_GLOBAL_CAP", "256")
    d = dict(cache_size=1 << 12, sweep_interval_ms=0,
             global_mode="mesh", batch_rows=64,
             behaviors=BehaviorConfig(global_sync_wait_ms=SYNC_MS))
    d.update(cfg)
    return V1Instance(Config(**d), mesh=make_mesh(n=n))


def seeded_traffic(inst, waves=4, keys=5, hits=2, name="mg"):
    """Deterministic GLOBAL wire traffic; returns the response bytes."""
    outs = []
    for w in range(waves):
        reqs = [greq(f"k{i % keys}", hits=hits, name=name)
                for i in range(4 * keys)]
        outs.append(inst.get_rate_limits_wire(ser(reqs),
                                              now_ms=NOW + 1 + w))
    return outs


def test_conservation_convergence_staleness(monkeypatch):
    """The acceptance dryrun: GLOBAL hits reconcile over the mesh with
    exact conservation (sum of shard counters == injected hits), every
    replica converges after the fold, measured staleness stays within
    the configured reconcile interval, and NOTHING was ever queued for
    a gRPC peer."""
    inst = mesh_instance(monkeypatch)
    try:
        seeded_traffic(inst)
        # object lane rides the same tier
        r = inst.get_rate_limits([greq("k0", hits=3)], now_ms=NOW + 50)
        assert r[0].error == "" and r[0].status == Status.UNDER_LIMIT
        inst._mesh_reconcile_tick()
        mge = inst._meshglobal
        mge.drain()
        s = mge.stats()
        injected = 4 * 20 * 2 + 3
        assert s["injected_hits"] == injected
        assert s["folded_hits"] == injected, s  # exact conservation
        assert s["generation"] >= 1
        # staleness ≤ the configured reconcile interval
        assert s["last_staleness_s"] * 1000 <= SYNC_MS, s
        assert float(
            inst.metrics.mesh_global_staleness._value.get()) * 1000 \
            <= SYNC_MS
        # every replica of every pinned key agrees post-fold
        for kh, slot in mge.slots.items():
            col = np.asarray(mge.state.remaining)[:, slot]
            assert len(set(col.tolist())) == 1, (kh, col)
        # k0: 4 waves × 4 occurrences × 2 hits + 3 object-lane hits
        kh0 = hash_key("mg", "k0")
        rem = np.asarray(mge.state.remaining)[0, mge.slots[kh0]]
        assert int(rem) == 100_000 - (4 * 4 * 2 + 3)
        # zero gRPC peer RPCs: no peers, and no hit aggregate was ever
        # queued for the gRPC lanes
        gm = inst.global_manager
        assert gm is not None and not gm._hits and not gm._hits_raw
        assert inst.metrics.check_error_counter.labels(
            error="global_hits_sync")._value.get() == 0
        # waves are stamped with the coherence epoch
        assert inst.dispatcher.reconcile_gen == mge.generation
    finally:
        inst.close()


def test_bit_identical_vs_grpc_path(monkeypatch):
    """Same seeded traffic through mesh mode and through the gRPC-mode
    solo path (hot set off → owner-sharded GLOBAL): response bytes
    must match bit for bit — home-shard routing makes the mesh
    replica's decisions exactly the owner-sharded decisions."""
    mi = mesh_instance(monkeypatch)
    try:
        mesh_outs = seeded_traffic(mi)
        m_obj = mi.get_rate_limits([greq("k1", hits=5)], now_ms=NOW + 60)
    finally:
        mi.close()
    gi = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0,
                           hot_set_capacity=0, batch_rows=64),
                    mesh=make_mesh(n=8))
    try:
        grpc_outs = seeded_traffic(gi)
        g_obj = gi.get_rate_limits([greq("k1", hits=5)], now_ms=NOW + 60)
        assert grpc_outs == mesh_outs
        assert (g_obj[0].status, g_obj[0].remaining, g_obj[0].reset_time,
                g_obj[0].limit) == \
               (m_obj[0].status, m_obj[0].remaining, m_obj[0].reset_time,
                m_obj[0].limit)
    finally:
        gi.close()


def test_chaos_collective_fault_conservation(monkeypatch):
    """A collective faultpoint armed mid-traffic: reconcile ticks abort
    (accumulators swap back — no hit stranded), and once the fault
    clears ONE clean fold recovers exact conservation."""
    inst = mesh_instance(monkeypatch)
    try:
        seeded_traffic(inst, waves=2)
        inst.faults.arm("global_psum:error", seed=11)
        inst._mesh_reconcile_tick()  # aborts; swap-back keeps the hits
        assert inst.metrics.mesh_global_fold_errors._value.get() >= 1
        seeded_traffic(inst, waves=2)  # more hits while degraded
        inst.faults.arm("global_accum_swap:error", seed=11)
        inst._mesh_reconcile_tick()  # aborts before the swap
        inst.faults.clear()
        inst._mesh_reconcile_tick()  # one clean fold recovers all
        mge = inst._meshglobal
        mge.drain()
        s = mge.stats()
        assert s["folded_hits"] == s["injected_hits"] == 4 * 20 * 2, s
    finally:
        inst.close()


def test_degraded_fallback_and_recovery(monkeypatch):
    """Consecutive fold failures stand the tier down: keys demote to
    the owner-sharded path EXACTLY (home-row migration needs no
    collective), traffic keeps serving, and a clean fold after the
    cooldown re-arms the tier."""
    monkeypatch.setenv("GUBER_MESH_FALLBACK_AFTER", "2")
    inst = mesh_instance(monkeypatch,
                         behaviors=BehaviorConfig(
                             global_sync_wait_ms=60_000))
    try:
        seeded_traffic(inst, waves=2, keys=3)
        inst._mesh_reconcile_tick()  # clean fold applies the backlog
        inst.faults.arm("global_psum:error", seed=3)
        inst._mesh_reconcile_tick()
        assert not inst._mesh_degraded
        inst._mesh_reconcile_tick()  # streak hits the threshold
        assert inst._mesh_degraded
        assert inst.metrics.mesh_global_degraded._value.get() == 1
        mge = inst._meshglobal
        assert not mge.pinned_keys()  # demoted to the sharded table
        # consumption survived the stand-down: the sharded row carries
        # every hit (2 waves × 4 occurrences × 2 hits = 16 on k0,
        # folded into the replica then migrated home)
        kh0 = hash_key("mg", "k0")
        found, cols = inst.engine.gather_rows(np.array([kh0], np.uint64))
        assert found[0]
        assert int(cols["remaining"][0]) == 100_000 - 16
        # degraded traffic serves from the sharded path, still exact
        out = pb.GetRateLimitsResp.FromString(
            inst.get_rate_limits_wire(ser([greq("k0", hits=1)]),
                                      now_ms=NOW + 200))
        assert out.responses[0].error == ""
        assert out.responses[0].remaining == 100_000 - 17
        # recovery: clean folds after the cooldown re-arm the tier
        inst.faults.clear()
        inst._mesh_down_until = time.monotonic() - 1
        inst._mesh_reconcile_tick()
        assert not inst._mesh_degraded
        assert inst.metrics.mesh_global_degraded._value.get() == 0
        # and routing resumes on the mesh tier
        inst.get_rate_limits_wire(ser([greq("k0", hits=1)]),
                                  now_ms=NOW + 300)
        assert mge.pinned_keys()
    finally:
        inst.close()


def test_config_change_demotes_with_state(monkeypatch):
    """A limit change on a mesh-pinned key demotes it (state intact)
    and the new config applies — the hot set's contract, kept."""
    inst = mesh_instance(monkeypatch)
    try:
        inst.get_rate_limits([greq("cfg", hits=11, limit=100)],
                             now_ms=NOW)
        kh = hash_key("mg", "cfg")
        assert inst._meshglobal.is_pinned(kh)
        r = inst.get_rate_limits([greq("cfg", hits=1, limit=50)],
                                 now_ms=NOW + 1)[0]
        assert not inst._meshglobal.is_pinned(kh)
        assert r.limit == 50
        # 11 consumed at limit 100 → 89; limit 100→50 adjusts by -50
        # → clamp(39, 0, 50); this hit takes 1 → 38
        assert r.remaining == 38, r
    finally:
        inst.close()


def test_flagged_requests_bypass_mesh(monkeypatch):
    """RESET/DRAIN/Gregorian/MULTI_REGION-flagged GLOBAL rows never
    enter the mesh tier (the hot set's exclusion rule)."""
    inst = mesh_instance(monkeypatch)
    try:
        r = inst.get_rate_limits(
            [greq("flg", behavior=Behavior.GLOBAL
                  | Behavior.RESET_REMAINING)], now_ms=NOW)[0]
        assert r.error == ""
        mge = inst._meshglobal
        assert mge is None or not mge.pinned_keys()
    finally:
        inst.close()


def test_grpc_mode_untouched_by_default(monkeypatch):
    """The default mode stays grpc: no mesh tier is ever built, and
    the hot set keeps its job."""
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      mesh=make_mesh(n=4))
    try:
        assert inst._global_mode == "grpc"
        inst.get_rate_limits([greq("g0")], now_ms=NOW)
        assert inst._meshglobal is None
    finally:
        inst.close()


def test_unknown_global_mode_is_loud():
    with pytest.raises(ValueError, match="global_mode"):
        V1Instance(Config(cache_size=1 << 10, global_mode="typo"),
                   mesh=make_mesh(n=1))


def test_sketch_feeds_hotset_promotion(monkeypatch):
    """ISSUE 7 satellite (the PR-4 ROADMAP hook): the Space-Saving
    heavy-hitter ledger drives hot-set promotion.  A key made hot by
    NON-GLOBAL traffic (which never touched the ad-hoc promotion
    counter) promotes on its FIRST GLOBAL request, because the sketch
    already counts it past the threshold."""
    inst = V1Instance(
        Config(cache_size=1 << 10, sweep_interval_ms=0,
               hot_set_capacity=64, hot_promote_threshold=8,
               behaviors=BehaviorConfig(global_sync_wait_ms=25)),
        mesh=make_mesh(n=4))
    try:
        ana = inst.analytics
        if ana is None:
            pytest.skip("analytics disabled")
        plain = RateLimitRequest(name="mg", unique_key="skp", hits=1,
                                 limit=100_000, duration=600_000)
        for i in range(10):
            inst.get_rate_limits([plain], now_ms=NOW + i)
        assert ana.flush(), "analytics flush timed out"
        kh = hash_key("mg", "skp")
        assert ana.sketch_count(kh) >= 10
        assert inst._hot_counts.get(kh, 0) == 0  # ad-hoc never saw it
        inst.get_rate_limits([greq("skp")], now_ms=NOW + 20)
        assert inst._hotset is not None and inst._hotset.is_pinned(kh)
    finally:
        inst.close()

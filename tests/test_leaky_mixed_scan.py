"""Leaky mixed-arrival-time tails: the speculative associative-scan
path (core/step.py) must match the sequential oracle exactly — both
when the speculation holds (no denies: the scan's answer is adopted)
and when it fails (denies: the segment falls back to the while_loop).

reference: algorithms.go › leakyBucket applied per request in arrival
order — reconstructed, mount empty.  The engine packs merged callers
(distinct clocks) into one launch; parity target is the oracle applied
at each request's own time, ascending.
"""
import numpy as np
import pytest

from gubernator_tpu import Algorithm, Oracle, RateLimitRequest
from gubernator_tpu.core.batch import pack_requests
from gubernator_tpu.hashing import hash_request_keys
from gubernator_tpu.parallel import ShardedEngine, make_mesh

NOW = 1_700_000_000_000
HOUR = 3_600_000


@pytest.fixture(scope="module")
def engine():
    return ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                         batch_per_shard=256)


def run_merged(engine, jobs):
    """Pack per-time jobs into ONE launch (per-request now column) and
    return engine outputs + sequential oracle expectations."""
    oracle = Oracle()
    packed, want = [], []
    for reqs, now in jobs:
        kh = hash_request_keys([r.name for r in reqs],
                               [r.unique_key for r in reqs])
        b, errs = pack_requests(reqs, now, size=len(reqs), key_hashes=kh)
        assert not any(errs)
        packed.append((b, kh))
        want.extend(oracle.check_batch(reqs, now))
    batch = type(packed[0][0])(*[
        np.concatenate([np.asarray(p[0][f]) for p in packed])
        for f in range(len(packed[0][0]))])
    khash = np.concatenate([p[1] for p in packed])
    st, lim, rem, rst, full = engine.check_packed(batch, khash,
                                                  jobs[-1][1])
    assert not full.any()
    return st, lim, rem, rst, want


def leaky(key, hits, limit=50, burst=100, duration=HOUR, name="lms"):
    return RateLimitRequest(name=name, unique_key=key, hits=hits,
                            limit=limit, duration=duration,
                            algorithm=Algorithm.LEAKY_BUCKET, burst=burst)


def assert_parity(st, lim, rem, rst, want, ctx=""):
    for g, w in enumerate(want):
        assert (int(st[g]), int(rem[g]), int(rst[g]), int(lim[g])) == \
            (int(w.status), w.remaining, w.reset_time, w.limit), \
            (ctx, g, w)


def test_all_allowed_mixed_times_one_hot_key(engine):
    """The speculation-success case: one hot leaky key, 24 requests at
    8 distinct instants, generous burst — every position allowed, the
    whole tail rides the scan."""
    jobs = [([leaky("hot", hits=2)] * 3, NOW + i * 977) for i in range(8)]
    assert_parity(*run_merged(engine, jobs), "allow")


def test_denies_force_fallback_parity(engine):
    """Speculation-failure case: tight limit so mid-segment denies
    occur; the while_loop fallback must produce oracle parity too."""
    jobs = [([leaky("tight", hits=7, limit=3, burst=10)] * 2,
             NOW + i * 1733) for i in range(6)]
    st, lim, rem, rst, want = run_merged(engine, jobs)
    assert any(int(w.status) == 1 for w in want)  # denies really happened
    assert_parity(st, lim, rem, rst, want, "deny")


def test_replenish_between_instants(engine):
    """Arrival gaps large enough to replenish tokens change the allow
    pattern vs uniform-time application — exactly what the scan's
    min-plus composition must capture."""
    # limit 10/hour => 1 token per 360_000 ms; drain 5 then wait to
    # replenish a few
    jobs = [
        ([leaky("rep", hits=5, limit=10, burst=10)], NOW),
        ([leaky("rep", hits=5, limit=10, burst=10)], NOW + 1),
        ([leaky("rep", hits=3, limit=10, burst=10)], NOW + 2 * 360_000),
        ([leaky("rep", hits=1, limit=10, burst=10)], NOW + 2 * 360_000 + 5),
    ]
    assert_parity(*run_merged(engine, jobs), "replenish")


def test_expiry_crossing_inside_segment(engine):
    """A gap past the duration makes the bucket fresh mid-segment; for
    leaky this equals replenish saturation — the scan must agree."""
    jobs = [
        ([leaky("exp", hits=90, limit=50, burst=100, duration=10_000)], NOW),
        ([leaky("exp", hits=1, limit=50, burst=100, duration=10_000)],
         NOW + 25_000),  # past expiry: fresh bucket
        ([leaky("exp", hits=2, limit=50, burst=100, duration=10_000)],
         NOW + 25_001),
    ]
    assert_parity(*run_merged(engine, jobs), "expiry")


def test_expiry_crossing_burst_exceeds_limit(engine):
    """Regression (r2 code review): with burst > limit, an expiry
    crossing must reset the bucket to burst*eff (FRESH), not merely
    replenish d*limit — for eff <= d < (burst/limit)*eff those
    differ, and the under-filled bucket would wrongly deny the next
    burst-1 legitimate hits."""
    eff = 60_000
    jobs = [
        # drain a limit=1 burst=10 bucket to 0
        ([leaky("bl", hits=10, limit=1, burst=10, duration=eff)], NOW),
        # second arrival exactly one duration later: d == eff crosses
        # the expiry, but d*limit = eff << cap_td = 10*eff
        ([leaky("bl", hits=1, limit=1, burst=10, duration=eff)],
         NOW + eff),
        # the fresh bucket must now serve 9 more hits
        ([leaky("bl", hits=9, limit=1, burst=10, duration=eff)],
         NOW + eff + 1),
    ]
    st, lim, rem, rst, want = run_merged(engine, jobs)
    assert int(want[1].status) == 0 and want[1].remaining == 9
    assert_parity(st, lim, rem, rst, want, "burst>limit crossing")


def test_query_only_mixed_times(engine):
    """hits=0 queries at mixed instants: no consumption, status
    propagates (flipping to UNDER after an expiry crossing), remaining
    reflects replenishment."""
    # drain to OVER first, then query at later instants
    jobs = [
        ([leaky("q", hits=100, limit=50, burst=100, duration=10_000)], NOW),
        ([leaky("q", hits=100, limit=50, burst=100, duration=10_000)],
         NOW + 1),  # denied -> status OVER stored
        ([leaky("q", hits=0, limit=50, burst=100, duration=10_000)],
         NOW + 100),
        ([leaky("q", hits=0, limit=50, burst=100, duration=10_000)],
         NOW + 30_000),  # past expiry: fresh/full
    ]
    assert_parity(*run_merged(engine, jobs), "query")


def test_many_keys_mixed_scan_and_simple(engine):
    """A wave mixing: scan-eligible leaky segments, token segments (the
    existing closed form), singletons, and a deny-heavy leaky segment —
    every routing decision in one launch."""
    rng = np.random.default_rng(42)
    jobs = []
    for i in range(6):
        reqs = []
        for k in range(5):
            reqs.append(leaky(f"mk{k}", hits=int(rng.integers(1, 4)),
                              limit=30, burst=60))
        reqs.append(leaky("mtight", hits=9, limit=4, burst=8))
        reqs.append(RateLimitRequest(
            name="lms", unique_key="tok", hits=1, limit=100,
            duration=HOUR, algorithm=Algorithm.TOKEN_BUCKET))
        reqs.append(leaky(f"solo{i}", hits=1))
        jobs.append((reqs, NOW + i * 611 + int(rng.integers(0, 50))))
    assert_parity(*run_merged(engine, jobs), "mixed-wave")


def test_big_segment_scan_vs_loop_equivalence(engine):
    """256 mixed-time requests on one key, all allowed: the scan path
    must agree with the oracle across a long prefix chain (this is the
    shape whose while_loop cost motivated the scan)."""
    jobs = [([leaky("big", hits=1, limit=1000, burst=4000)],
             NOW + i * 37) for i in range(256)]
    assert_parity(*run_merged(engine, jobs), "big")

"""Native host-ops extension tests (skipped when not built)."""
import pytest

native = pytest.importorskip("gubernator_tpu.ops.native")

from gubernator_tpu.hashing import (  # noqa: E402
    fnv1a64,
    hash_key,
    hash_keys,
    hash_request_keys,
)


def test_raw_fnv_matches_python():
    keys = ["", "a", "load_k42", "πδ∞ unicode", "x" * 10_000]
    raw = native.hash_keys(keys)
    for k, h in zip(keys, raw):
        assert int(h) == fnv1a64(k.encode("utf-8"))


def test_pair_hash_equals_joined():
    names = ["svc", "", "a_b"]
    uks = ["user:1", "k", ""]
    assert (native.hash_pairs(names, uks)
            == native.hash_keys([f"{n}_{u}" for n, u in zip(names, uks)])).all()


def test_hash_request_keys_matches_scalar():
    names = [f"n{i}" for i in range(100)]
    uks = [f"u{i}" for i in range(100)]
    batch = hash_request_keys(names, uks)
    for i in range(100):
        assert int(batch[i]) == hash_key(names[i], uks[i])


def test_hash_keys_native_equals_fallback():
    import gubernator_tpu.hashing as H

    keys = [f"mixed_{i}" for i in range(1000)]
    with_native = hash_keys(keys)
    saved, H._native = H._native, None
    try:
        without = hash_keys(keys)
    finally:
        H._native = saved
    assert (with_native == without).all()


def test_errors():
    with pytest.raises(TypeError):
        native.hash_keys([1, 2, 3])
    with pytest.raises(ValueError):
        native.hash_pairs(["a"], ["b", "c"])

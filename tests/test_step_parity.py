"""M1 parity harness: device decide_batch vs the M0 oracle, bit-for-bit.

The north-star requires allow/deny parity with the reference semantics
(BASELINE.md); the oracle is the executable form of that contract, so
every stream here asserts exact equality of (status, remaining,
reset_time, limit) for every request.
"""
import numpy as np
import pytest

from gubernator_tpu import Algorithm, Behavior, GregorianDuration, Oracle, RateLimitRequest
from gubernator_tpu.core import decide_batch, init_table, pack_requests

NOW = 1_760_000_000_000
CAP = 1 << 14


def run_stream(batches, cap=CAP):
    """batches: list of (reqs, now_ms). Returns list of mismatches."""
    oracle = Oracle()
    state = init_table(cap)
    mismatches = []
    for bi, (reqs, now) in enumerate(batches):
        want = oracle.check_batch(reqs, now)
        packed, errs = pack_requests(reqs, now)
        state, out = decide_batch(state, packed, now)
        status = np.asarray(out.status)
        rem = np.asarray(out.remaining)
        rst = np.asarray(out.reset_time)
        lim = np.asarray(out.limit)
        err = np.asarray(out.err)
        for i, w in enumerate(want):
            if errs[i]:
                continue  # host-side rejected (e.g. bad gregorian ordinal)
            if err[i]:
                mismatches.append((bi, i, "table-full", None, None))
                continue
            got = (int(status[i]), int(rem[i]), int(rst[i]), int(lim[i]))
            exp = (int(w.status), int(w.remaining), int(w.reset_time), int(w.limit))
            if got != exp:
                mismatches.append((bi, i, reqs[i], exp, got))
    return mismatches


def assert_parity(batches, cap=CAP):
    mm = run_stream(batches, cap)
    assert not mm, f"{len(mm)} mismatches; first 5: {mm[:5]}"


def mk(name="t", key="k", **kw):
    d = dict(hits=1, limit=10, duration=60_000, algorithm=Algorithm.TOKEN_BUCKET)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, **d)


class TestBasicParity:
    def test_single_key_token(self):
        batches = [([mk()] , NOW + i * 100) for i in range(15)]
        assert_parity(batches)

    def test_single_key_leaky(self):
        batches = [([mk(algorithm=Algorithm.LEAKY_BUCKET)], NOW + i * 700)
                   for i in range(30)]
        assert_parity(batches)

    def test_many_unique_keys(self):
        batches = []
        for t in range(5):
            reqs = [mk(key=f"k{i}", hits=1 + i % 3, limit=5 + i % 7)
                    for i in range(100)]
            batches.append((reqs, NOW + t * 1000))
        assert_parity(batches)

    def test_expiry_across_batches(self):
        batches = [
            ([mk(hits=10)], NOW),
            ([mk(hits=1)], NOW + 59_999),   # still over
            ([mk(hits=1)], NOW + 60_000),   # reset
            ([mk(hits=1)], NOW + 200_000),  # reset again
        ]
        assert_parity(batches)

    def test_hits_zero_queries(self):
        batches = [
            ([mk(hits=3)], NOW),
            ([mk(hits=0)], NOW + 1),
            ([mk(hits=100)], NOW + 2),
            ([mk(hits=0)], NOW + 3),  # stored OVER status
        ]
        assert_parity(batches)


class TestDuplicateKeyParity:
    def test_uniform_duplicates_closed_form(self):
        # 7 identical requests for one key in one batch: 5 admitted
        batches = [([mk(limit=5) for _ in range(7)], NOW)]
        assert_parity(batches)

    def test_uniform_duplicates_multi_hit(self):
        batches = [([mk(hits=3, limit=10) for _ in range(5)], NOW)]
        assert_parity(batches)

    def test_mixed_hits_loop_path(self):
        # remaining=5: [3,4,2] → ok, over, ok — the sequential trap
        batches = [
            ([mk(hits=5, limit=10)], NOW),
            ([mk(hits=3), mk(hits=4), mk(hits=2)], NOW + 1),
        ]
        assert_parity(batches)

    def test_mixed_flags_loop_path(self):
        reqs = [
            mk(hits=8),
            mk(hits=5),  # over
            mk(hits=1, behavior=Behavior.RESET_REMAINING),
            mk(hits=4, behavior=Behavior.DRAIN_OVER_LIMIT | Behavior.BATCHING),
            mk(hits=20, behavior=Behavior.DRAIN_OVER_LIMIT),  # over → drain
            mk(hits=0),
        ]
        assert_parity([(reqs, NOW)])

    def test_duplicates_among_many_keys(self):
        rng = np.random.default_rng(0)
        batches = []
        for t in range(4):
            reqs = []
            for _ in range(200):
                k = f"k{rng.integers(0, 30)}"
                reqs.append(mk(key=k, hits=int(rng.integers(0, 4)), limit=20))
            batches.append((reqs, NOW + t * 5_000))
        assert_parity(batches)

    def test_config_change_within_batch(self):
        batches = [(
            [mk(hits=1, limit=100), mk(hits=1, limit=50), mk(hits=1, limit=200)],
            NOW,
        )]
        assert_parity(batches)

    def test_new_key_duplicates_in_one_batch(self):
        # both duplicates miss, must resolve to the SAME row
        batches = [([mk(key="brand-new", limit=3) for _ in range(5)], NOW)]
        assert_parity(batches)


class TestBehaviorParity:
    def test_reset_remaining(self):
        batches = [
            ([mk(hits=10)], NOW),
            ([mk(hits=2, behavior=Behavior.RESET_REMAINING)], NOW + 1),
        ]
        assert_parity(batches)

    def test_drain_over_limit(self):
        batches = [
            ([mk(hits=7)], NOW),
            ([mk(hits=5, behavior=Behavior.DRAIN_OVER_LIMIT)], NOW + 1),
        ]
        assert_parity(batches)

    def test_gregorian_token(self):
        b = Behavior.DURATION_IS_GREGORIAN
        batches = [
            ([mk(hits=2, duration=GregorianDuration.MINUTES, behavior=b)], NOW),
            ([mk(hits=2, duration=GregorianDuration.MINUTES, behavior=b)], NOW + 30_000),
            ([mk(hits=2, duration=GregorianDuration.MINUTES, behavior=b)], NOW + 70_000),
        ]
        assert_parity(batches)

    def test_invalid_gregorian_is_host_error(self):
        reqs = [mk(duration=99, behavior=Behavior.DURATION_IS_GREGORIAN), mk(key="ok")]
        packed, errs = pack_requests(reqs, NOW)
        assert "invalid gregorian" in errs[0]
        assert errs[1] == ""
        assert not packed.valid[0] and packed.valid[1]

    def test_leaky_burst_and_duration_change(self):
        L = Algorithm.LEAKY_BUCKET
        batches = [
            ([mk(algorithm=L, hits=4, burst=20)], NOW),
            ([mk(algorithm=L, hits=0, duration=120_000, burst=20)], NOW + 500),
            ([mk(algorithm=L, hits=3, duration=120_000, burst=20)], NOW + 1_000),
        ]
        assert_parity(batches)

    def test_algorithm_switch(self):
        batches = [
            ([mk(hits=5)], NOW),
            ([mk(hits=1, algorithm=Algorithm.LEAKY_BUCKET)], NOW + 1),
            ([mk(hits=1)], NOW + 2),
        ]
        assert_parity(batches)


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_random_stream(self, seed):
        rng = np.random.default_rng(seed)
        algs = [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
        behs = [Behavior.BATCHING, Behavior.RESET_REMAINING,
                Behavior.DRAIN_OVER_LIMIT]
        batches = []
        now = NOW
        for _ in range(6):
            reqs = []
            for _ in range(int(rng.integers(1, 120))):
                reqs.append(RateLimitRequest(
                    name=f"n{rng.integers(0, 3)}",
                    unique_key=f"u{rng.integers(0, 40)}",
                    hits=int(rng.integers(0, 6)),
                    limit=int(rng.integers(1, 30)),
                    duration=int(rng.choice([1_000, 10_000, 60_000])),
                    algorithm=algs[int(rng.integers(0, 2))],
                    behavior=behs[int(rng.integers(0, 3))],
                    burst=int(rng.choice([0, 0, 15])),
                ))
            batches.append((reqs, now))
            now += int(rng.integers(0, 20_000))
        assert_parity(batches)

    def test_zipf_stream(self):
        rng = np.random.default_rng(7)
        batches = []
        now = NOW
        for _ in range(5):
            ks = rng.zipf(1.5, size=256) % 500
            reqs = [mk(key=f"z{k}", limit=50) for k in ks]
            batches.append((reqs, now))
            now += 3_000
        assert_parity(batches)


def test_donated_step_matches_copy_step():
    """The SERVING default (decide_batch_donated: same impl, table
    donated in/out) must produce outputs and final state bit-identical
    to the non-donated step on the same stream — guards against any
    aliasing misuse at the call boundary (a donated input is dead after
    the call; nothing may re-read it)."""
    from gubernator_tpu.core.step import decide_batch_donated

    rng = np.random.default_rng(3)
    stc = init_table(1 << 12)
    std = init_table(1 << 12)
    for step_i in range(6):
        reqs = [RateLimitRequest(
            name="dm", unique_key=f"k{int(k)}",
            hits=int(rng.integers(0, 3)), limit=20, duration=60_000,
            algorithm=Algorithm.LEAKY_BUCKET if k % 3 == 0
            else Algorithm.TOKEN_BUCKET,
            behavior=Behavior.RESET_REMAINING if k % 17 == 0
            else Behavior.BATCHING)
            for k in rng.integers(0, 60, size=128)]
        now = NOW + step_i * 1000
        packed, _ = pack_requests(reqs, now)
        stc, outc = decide_batch(stc, packed, now)
        std, outd = decide_batch_donated(std, packed, now)
        for f in ("status", "remaining", "reset_time", "limit", "err"):
            np.testing.assert_array_equal(
                np.asarray(getattr(outc, f)), np.asarray(getattr(outd, f)),
                err_msg=f"step {step_i}: {f} diverged")
    for i, (c, d) in enumerate(zip(stc, std)):
        np.testing.assert_array_equal(
            np.asarray(c), np.asarray(d),
            err_msg=f"final state col {i} diverged")

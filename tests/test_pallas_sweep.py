"""Pallas fused sweep kernel vs. the XLA reference implementation.

Runs in Pallas interpret mode on the CPU mesh (the sandbox's real-TPU
path uses the compiled kernel; semantics are identical by construction).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from gubernator_tpu.core.table import init_table, occupancy, sweep_expired
from gubernator_tpu.ops.pallas_sweep import sweep_expired_pallas

NOW = 1_767_000_000_000


def populated_table(cap=2048, n=500, seed=0):
    rng = np.random.default_rng(seed)
    state = init_table(cap)
    rows = rng.choice(cap, size=n, replace=False)
    key = np.zeros(cap, np.uint64)
    key[rows] = rng.integers(1, 2**63, size=n).astype(np.uint64)
    # include keys with high bit set (uint64 edge) and huge expiries
    key[rows[0]] = np.uint64(2**64 - 17)
    exp = np.zeros(cap, np.int64)
    exp[rows] = NOW + rng.integers(-50_000, 50_000, size=n)
    exp[rows[1]] = NOW  # boundary: expire_at == now is dead
    exp[rows[2]] = 2**62  # far future
    return state._replace(key=jnp.asarray(key), expire_at=jnp.asarray(exp))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_matches_xla_sweep(seed):
    state = populated_table(seed=seed)
    want = sweep_expired(state, np.int64(NOW))
    got, live = sweep_expired_pallas(state, np.int64(NOW), interpret=True)
    for f in state._fields:
        assert (np.asarray(getattr(got, f))
                == np.asarray(getattr(want, f))).all(), f
    assert int(live) == int(occupancy(want))


def test_empty_and_full():
    state = init_table(1024)
    got, live = sweep_expired_pallas(state, np.int64(NOW), interpret=True)
    assert int(live) == 0
    # all live
    key = np.arange(1, 1025, dtype=np.uint64)
    exp = np.full(1024, NOW + 1, np.int64)
    state = state._replace(key=jnp.asarray(key), expire_at=jnp.asarray(exp))
    got, live = sweep_expired_pallas(state, np.int64(NOW), interpret=True)
    assert int(live) == 1024
    assert (np.asarray(got.key) == key).all()


def test_capacity_validation():
    state = init_table(512)  # < one (8,128) tile
    with pytest.raises(ValueError, match="multiple"):
        sweep_expired_pallas(state, np.int64(NOW), interpret=True)


def test_engine_pallas_sweep_path(monkeypatch, cpu_mesh):
    """GUBER_PALLAS_SWEEP=1: the engine's sweep runs the shard_map'd
    kernel and produces the same decisions as the XLA path."""
    from gubernator_tpu.parallel import ShardedEngine
    from gubernator_tpu.types import RateLimitRequest

    monkeypatch.setenv("GUBER_PALLAS_SWEEP", "1")
    eng = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    reqs = [RateLimitRequest(name="ps", unique_key=f"k{i}", hits=1,
                             limit=5, duration=5_000) for i in range(40)]
    eng.check_batch(reqs, NOW)
    eng.sweep(NOW + 1)  # nothing expired yet
    assert eng.live_rows == 40
    eng.sweep(NOW + 10_000)  # everything expired
    assert eng.live_rows == 0
    # swept rows behave as fresh on next access
    out = eng.check_batch(reqs, NOW + 20_000)
    assert all(r.remaining == 4 for r in out)

"""Elasticity: membership churn, failure handling, re-sharding
(reference: SetPeers → picker rebuild + PeerClient drain; SURVEY.md
§5.3 — keys silently re-home, moved state resets; §7.3 re-sharding)."""
import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.client import Client
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.types import RateLimitRequest


def req(name, key, **kw):
    d = dict(hits=1, limit=10, duration=60_000)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, **d)


def test_snapshot_restores_across_shard_counts():
    """The snapshot is shard-count independent: a 4-shard table restores
    into a 2-shard engine (rows re-route by hash range) — the intra-node
    re-sharding story for topology changes."""
    now = 1_766_000_000_000
    e4 = ShardedEngine(make_mesh(n=4), capacity_per_shard=1 << 10,
                       batch_per_shard=64)
    reqs = [req("resh", f"k{i}", hits=3, limit=9) for i in range(50)]
    e4.check_batch(reqs, now)
    snap = e4.snapshot()

    e2 = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 11,
                       batch_per_shard=64)
    assert e2.restore(snap) == 50
    out = e2.check_batch([req("resh", f"k{i}", hits=0, limit=9)
                          for i in range(50)], now + 5)
    assert all(r.remaining == 6 for r in out)

    e1 = ShardedEngine(make_mesh(n=1), capacity_per_shard=1 << 12,
                       batch_per_shard=64)
    assert e1.restore(snap) == 50
    out = e1.check_batch([req("resh", f"k{i}", hits=6, limit=9)
                          for i in range(50)], now + 10)
    assert all((int(r.status), r.remaining) == (0, 0) for r in out)


@pytest.fixture(scope="module")
def churn_cluster():
    c = cluster_mod.start(3, mesh=make_mesh(n=2),
                          behaviors=BehaviorConfig(batch_timeout_ms=30))
    yield c
    c.stop()


def test_daemon_departure_keys_rehome(churn_cluster):
    """Stop one daemon; the survivors re-pick owners and keep serving.
    State owned by the departed daemon resets (documented reference
    behavior) — but service availability is uninterrupted."""
    c = churn_cluster
    with Client(c.grpc_address(0)) as cl:
        rs = cl.get_rate_limits([req("churn", f"k{i}") for i in range(30)])
        assert all(r.error == "" for r in rs)

    # daemon 2 leaves: remaining daemons get the shrunk peer list
    departed = c.daemons[2]
    survivors = [c.daemons[0], c.daemons[1]]
    infos = [d.peer_info() for d in survivors]
    for d in survivors:
        d.set_peers(infos)
    departed.close()

    with Client(c.grpc_address(0)) as cl:
        rs = cl.get_rate_limits([req("churn", f"k{i}") for i in range(30)])
        assert all(r.error == "" for r in rs), [r.error for r in rs if r.error]
        # every key is served; re-homed ones restart at limit-1, others
        # continue at limit-2
        assert {r.remaining for r in rs} <= {8, 9}
    h = survivors[0].instance.health_check()
    assert h.peer_count == 2

    # bring a replacement back on the departed daemon's addresses
    c.daemons[2] = cluster_mod.spawn_daemon(
        departed.cfg, mesh=survivors[0].instance.engine.mesh)
    infos = [d.peer_info() for d in c.daemons]
    for d in c.daemons:
        d.set_peers(infos)
    with Client(c.grpc_address(2)) as cl:
        rs = cl.get_rate_limits([req("churn", f"k{i}") for i in range(30)])
        assert all(r.error == "" for r in rs)


def test_forward_error_surfaces_per_request(churn_cluster):
    """A dead peer in the ring must surface per-request — never as an
    exception.  Since ISSUE 5 the default surface is a DEGRADED local
    answer (flagged, hits queued for reconcile) instead of an error row
    (gubernator.go wraps peer failures in resp.Error; that legacy
    error-row shape is pinned with peer_degraded_fallback=False in
    tests/test_resilience.py)."""
    c = churn_cluster
    inst = c.instance_at(0)
    from gubernator_tpu.types import PeerInfo

    live = [d.peer_info() for d in c.daemons]
    dead = PeerInfo(grpc_address="127.0.0.1:1")  # nothing listens here
    inst.set_peers(live + [dead])
    try:
        # find keys owned by the dead peer
        victims = [k for k in (f"dead{i}" for i in range(200))
                   if inst.owner_of(f"churn_{k}") is not None
                   and inst.owner_of(f"churn_{k}").info.grpc_address
                   == "127.0.0.1:1"][:3]
        assert victims, "no keys landed on the dead peer"
        rs = inst.get_rate_limits([req("churn", k) for k in victims])
        assert all(r.error == ""
                   and r.metadata.get("degraded") == "true"
                   and r.metadata.get("degraded_peer") == "127.0.0.1:1"
                   for r in rs)
    finally:
        inst.set_peers(live)

"""Replicated hot-set GLOBAL engine tests (SURVEY.md §2.3 — the psum
replacement for global.go's hit-queue + broadcast machinery)."""
import numpy as np
import pytest

from gubernator_tpu.hashing import hash_key
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.parallel.hotset import HotSetEngine
from gubernator_tpu.types import RateLimitRequest, Status

NOW = 1_764_000_000_000


def req(key="hk", limit=100, hits=1, duration=60_000):
    return RateLimitRequest(name="hot", unique_key=key, hits=hits,
                            limit=limit, duration=duration)


def kh(key="hk"):
    return hash_key("hot", key)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(n=4)


def test_pin_and_serve_single_requests(mesh4):
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    assert eng.pin(req(), kh(), NOW)
    assert eng.pin(req(), kh(), NOW)  # idempotent
    r = eng.check_batch([req(hits=3)], [kh()], NOW)[0]
    assert r.error == ""
    assert (int(r.status), r.remaining) == (0, 97)


def test_replicas_diverge_then_psum_converges(mesh4):
    """Each chip consumes locally; one sync() folds all consumption."""
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    eng.pin(req(limit=1000), kh("c"), NOW)
    # 40 hits spread round-robin over 4 replicas (10 each)
    rs = eng.check_batch([req("c", limit=1000) for _ in range(40)],
                         [kh("c")] * 40, NOW + 1)
    assert all(r.status == Status.UNDER_LIMIT for r in rs)
    # before sync, each replica only saw its own 10 hits
    per_replica_rem = {r.remaining for r in rs}
    assert min(per_replica_rem) >= 1000 - 40 // eng.n - 1
    eng.sync()
    # after sync every replica agrees on the merged count
    rs = eng.check_batch([req("c", limit=1000, hits=0)
                          for _ in range(eng.n)], [kh("c")] * eng.n, NOW + 2)
    assert {r.remaining for r in rs} == {960}


def test_conservation_across_syncs(mesh4):
    """Total admitted ≤ limit once syncs run between windows."""
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    eng.pin(req("cons", limit=50), kh("cons"), NOW)
    admitted = 0
    for wave in range(10):
        rs = eng.check_batch([req("cons", limit=50) for _ in range(10)],
                             [kh("cons")] * 10, NOW + wave)
        admitted += sum(1 for r in rs if r.status == Status.UNDER_LIMIT)
        eng.sync()
    assert admitted == 50  # exact: sync after every wave removes any window
    rs = eng.check_batch([req("cons", limit=50, hits=0)], [kh("cons")],
                         NOW + 100)
    assert rs[0].remaining == 0


def test_bounded_over_admission_within_window(mesh4):
    """Without syncs, over-admission is bounded by n_chips × limit —
    the documented GLOBAL eventual-consistency window."""
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=64)
    eng.pin(req("w", limit=10), kh("w"), NOW)
    rs = eng.check_batch([req("w", limit=10) for _ in range(200)],
                         [kh("w")] * 200, NOW + 1)
    admitted = sum(1 for r in rs if r.status == Status.UNDER_LIMIT)
    assert 10 <= admitted <= 10 * eng.n
    eng.sync()
    rs = eng.check_batch([req("w", limit=10, hits=0)], [kh("w")], NOW + 2)
    assert rs[0].remaining == 0  # clamped at zero after the fold


def test_expiry_refresh_merges(mesh4):
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    eng.pin(req("e", limit=20, duration=1_000), kh("e"), NOW)
    eng.check_batch([req("e", limit=20, duration=1_000)] * 8,
                    [kh("e")] * 8, NOW + 1)
    eng.sync()
    # past expiry: replicas refresh; merged state adopts the refresh
    rs = eng.check_batch([req("e", limit=20, duration=1_000)] * 8,
                         [kh("e")] * 8, NOW + 5_000)
    assert all(r.status == Status.UNDER_LIMIT for r in rs)
    eng.sync()
    rs = eng.check_batch([req("e", limit=20, duration=1_000, hits=0)],
                         [kh("e")], NOW + 5_001)
    assert rs[0].remaining == 20 - 8


def lreq(key="lk", limit=1000, hits=1, duration=60_000, burst=0):
    from gubernator_tpu.types import Algorithm

    return RateLimitRequest(name="hot", unique_key=key, hits=hits,
                            limit=limit, duration=duration, burst=burst,
                            algorithm=Algorithm.LEAKY_BUCKET)


def test_leaky_pin_and_serve(mesh4):
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    assert eng.pin(lreq(), kh("lk"), NOW)
    r = eng.check_batch([lreq(hits=3)], [kh("lk")], NOW + 1)[0]
    assert r.error == ""
    assert (int(r.status), r.remaining) == (0, 997)


def test_leaky_replicas_diverge_then_psum_converges(mesh4):
    """Leaky consumption folds across replicas like token consumption;
    the merge measures each replica against the replenished base."""
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    eng.pin(lreq("lc"), kh("lc"), NOW)
    rs = eng.check_batch([lreq("lc") for _ in range(40)],
                         [kh("lc")] * 40, NOW + 1)
    assert all(r.status == Status.UNDER_LIMIT for r in rs)
    # pre-sync each replica only saw its own share
    assert min(r.remaining for r in rs) >= 1000 - 40 // eng.n - 1
    eng.sync()
    rs = eng.check_batch([lreq("lc", hits=0) for _ in range(eng.n)],
                         [kh("lc")] * eng.n, NOW + 2)
    # 1ms of replenish at 1000/60s is < 1 token: floor stays at 960
    assert {r.remaining for r in rs} == {960}


def test_leaky_conservation_across_syncs(mesh4):
    """Sync after every wave ⇒ exactly burst admissions while replenish
    rounds to zero tokens."""
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    eng.pin(lreq("lcons", limit=50), kh("lcons"), NOW)
    admitted = 0
    for wave in range(10):
        rs = eng.check_batch([lreq("lcons", limit=50) for _ in range(10)],
                             [kh("lcons")] * 10, NOW + wave)
        admitted += sum(1 for r in rs if r.status == Status.UNDER_LIMIT)
        eng.sync()
    assert admitted == 50
    rs = eng.check_batch([lreq("lcons", limit=50, hits=0)], [kh("lcons")],
                         NOW + 100)
    assert rs[0].remaining == 0


def test_leaky_replenish_after_merged_drain(mesh4):
    """Post-sync the merged bucket leaks at limit/duration: half the
    duration replenishes half the limit."""
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=64)
    eng.pin(lreq("lr", limit=100, duration=1_000), kh("lr"), NOW)
    rs = eng.check_batch([lreq("lr", limit=100, duration=1_000)] * 100,
                         [kh("lr")] * 100, NOW + 1)
    assert all(r.status == Status.UNDER_LIMIT for r in rs)
    eng.sync()
    rs = eng.check_batch([lreq("lr", limit=100, duration=1_000, hits=0)],
                         [kh("lr")], NOW + 1)
    assert rs[0].remaining == 0  # fold drained the shared bucket
    rs = eng.check_batch([lreq("lr", limit=100, duration=1_000, hits=0)],
                         [kh("lr")], NOW + 501)
    assert rs[0].remaining == 50  # 500 ms × (100 per 1000 ms)


def test_mixed_algorithms_one_sync(mesh4):
    """Token and leaky rows coexist; one psum folds both correctly."""
    eng = HotSetEngine(mesh4, capacity=256, batch_per_chip=32)
    eng.pin(req("mt", limit=500), kh("mt"), NOW)
    eng.pin(lreq("ml"), kh("ml"), NOW)
    eng.check_batch([req("mt", limit=500)] * 20 + [lreq("ml")] * 20,
                    [kh("mt")] * 20 + [kh("ml")] * 20, NOW + 1)
    eng.sync()
    rs = eng.check_batch([req("mt", limit=500, hits=0), lreq("ml", hits=0)],
                         [kh("mt"), kh("ml")], NOW + 2)
    assert rs[0].remaining == 480
    assert rs[1].remaining == 980


def test_probe_window_exhaustion():
    mesh = make_mesh(n=2)
    eng = HotSetEngine(mesh, capacity=8, batch_per_chip=8)
    pinned = 0
    for i in range(64):
        if eng.pin(req(f"x{i}"), kh(f"x{i}"), NOW):
            pinned += 1
    assert 0 < pinned <= 8
    eng.unpin_all()
    assert eng.pin(req("x0"), kh("x0"), NOW)

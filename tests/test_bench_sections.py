"""The bench section protocol is the driver's contract: each secondary
config runs as a child process on device backends (bench.py ›
_run_section / _section_main), so a wedged tunnel compile costs one row
instead of the run.  Pin the child protocol itself on CPU: rows land in
the output file atomically, errors are contained, and a child whose
backend silently fell back refuses to mislabel its rows."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_section(name, tmp_path, extra_env=None, timeout=300):
    out = str(tmp_path / f"sec_{name}.json")
    env = dict(os.environ,
               GUBER_JAX_PLATFORM="cpu",
               GUBER_BENCH_SECTION=name,
               GUBER_BENCH_SECTION_OUT=out,
               GUBER_BENCH_FAST="1")
    env.update(extra_env or {})
    r = subprocess.run([sys.executable, BENCH], env=env, cwd=REPO,
                       timeout=timeout, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE)
    assert r.returncode == 0, r.stderr.decode()[-500:]
    with open(out) as f:
        return json.load(f)


def test_section_child_writes_rows(tmp_path):
    rows = _run_section("cfg12", tmp_path)
    assert set(rows) == {"1_single_key_smoke", "2_leaky_1k_keys"}
    for v in rows.values():
        assert v.get("decisions_per_s", 0) > 0, rows


def test_pallas_section_child_writes_row(tmp_path):
    """The fused-serving A/B row (11_pallas_serving, ISSUE 8) through
    the driver's real child protocol: compiled kernels (the interpret
    toy row is gone — its number lives under pre_pr), bit-identical
    fused-vs-xla decisions, the throughput ratio, and PhaseLedger
    evidence of the deleted pack phase.  Hostile GUBER_STEP_IMPL /
    GUBER_ENGINE exports must not flip the engines under measurement."""
    rows = _run_section("pallas", tmp_path, timeout=600,
                        extra_env={"GUBER_STEP_IMPL": "xla",
                                   "GUBER_ENGINE": "xla"})
    r = rows["11_pallas_serving"]
    assert r["engine"] == "xla_fused" and r["cpu_compiled"] is True
    assert r["compiled_kernels"] is True
    assert r["wire_lane_decisions_per_s"] > 0
    assert r["xla_wire_decisions_per_s"] > 0
    assert r["fused_vs_xla"] > 0
    assert r["ab_identical"] is True
    assert r["fused_waves"] > 0
    assert r["svc_p99_ms"] > 0
    assert r["pre_pr"]["wire_lane_decisions_per_s"] == 80411
    pd = r["phase_deleted"]
    assert pd["deleted_phase"] == "pack"
    assert pd["pack_absent_in_fused"] is True
    assert pd["pack_present_in_xla"] is True
    assert pd["partition_max_drift_ms"] <= 0.01
    assert "COMPILED" in r["context"]


def test_section_child_backend_mismatch_guard(tmp_path):
    """A child that lands on a different backend than the parent
    expected must produce an error row, not mislabeled numbers."""
    rows = _run_section("cfg12", tmp_path,
                        extra_env={"GUBER_BENCH_EXPECT_BACKEND": "tpu"})
    assert set(rows) == {"error"}
    assert "silent fallback" in rows["error"]


def test_mesh_global_section_child_writes_row(tmp_path):
    """The 12_mesh_global row (ISSUE 7) through the driver's real child
    protocol on an 8-device CPU mesh: the A/B must be bit-identical,
    conservation exact, staleness within the reconcile interval, and
    zero gRPC peer RPCs — the acceptance columns, pinned tier-1."""
    rows = _run_section(
        "mesh", tmp_path, timeout=600,
        extra_env={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=8"})
    r = rows["12_mesh_global"]
    assert r["n_shards"] == 8
    assert r["decisions_per_s"] > 0
    assert r["grpc_decisions_per_s"] > 0
    assert r["ab_identical"] is True
    assert r["conservation_exact"] is True
    assert r["staleness_within_interval"] is True
    assert r["zero_peer_rpcs"] is True
    assert r["reconcile_generations"] >= 1


def test_tiered_section_child_writes_row(tmp_path):
    """The 13_tiered_store row (ISSUE 10) through the driver's real
    child protocol: a device cap far below the key domain served
    through the host cold tier.  The verdict columns ARE the acceptance
    criteria — zero error rows on both sides, conservation exact across
    both tiers, decisions byte-identical to the uncapped oracle — with
    the capacity/migration story alongside."""
    rows = _run_section("tiered", tmp_path, timeout=600)
    r = rows["13_tiered_store"]
    assert r["device_cap_rows"] == 4096
    assert r["key_domain"] > r["device_cap_rows"]
    assert r["decisions_per_s"] > 0
    assert r["oracle_decisions_per_s"] > 0
    assert r["error_rows"] == 0
    assert r["oracle_error_rows"] == 0
    assert r["conservation_exact"] is True
    assert r["ab_identical"] is True
    assert r["cold_keys"] > 0
    assert r["cold_served"] > 0
    assert r["promotions"] > 0
    assert r["demotions"] == r["promotions"]
    assert r["migrations_aborted"] == 0
    assert 0 <= r["hot_hit_rate"] <= 1
    assert "cold_store_native" in r and "tier_vs_uncapped" in r


def test_tracing_ab_block_schema():
    """The 6_service_path ``tracing_ab`` block (ISSUE 12): pin the A/B
    schema — the armed-unsampled (<1%) and 1%-sampled (<3%) budget
    verdicts — by running the helper directly on a small instance (the
    full svc section is a device-backend child; the block's contract
    is what the driver greps)."""
    sys.path.insert(0, REPO)
    import bench
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.oracle import OracleEngine
    from gubernator_tpu.types import RateLimitRequest

    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        reqs = [RateLimitRequest(name="ab", unique_key=f"k{i}", hits=1,
                                 limit=1000, duration=60_000)
                for i in range(4)]
        row = bench._tracing_ab(
            inst, lambda r: inst.get_rate_limits(
                reqs, now_ms=1_791_000_000_000 + r),
            pairs=2, reps=4)
        assert "error" not in row, row
        for k in ("armed_overhead_pct", "overhead_ok",
                  "sampled_overhead_pct", "sampled_ok",
                  "off_calls_per_s", "pairs", "reps"):
            assert k in row, (k, row)
        assert isinstance(row["overhead_ok"], bool)
        assert isinstance(row["sampled_ok"], bool)
        assert row["off_calls_per_s"] > 0
        assert row["pairs"] == 2 and row["reps"] == 4
        # the A/B restores the recorder wiring it toggled
        assert inst.dispatcher.span_recorder is inst.span_recorder
        assert inst.span_recorder.sample == 0.0
    finally:
        inst.close()


def test_memledger_ab_block_schema():
    """The 6_service_path ``memledger_ab`` block (ISSUE 13): pin the
    A/B schema and its <1% steady-state budget verdict by running the
    helper directly on a small instance, and that the A/B leaves the
    ledger resumed (the toggle it flips must restore)."""
    sys.path.insert(0, REPO)
    import bench
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.oracle import OracleEngine
    from gubernator_tpu.types import RateLimitRequest

    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        assert inst.memledger is not None
        reqs = [RateLimitRequest(name="ab", unique_key=f"k{i}", hits=1,
                                 limit=1000, duration=60_000)
                for i in range(4)]
        row = bench._memledger_ab(
            inst, lambda r: inst.get_rate_limits(
                reqs, now_ms=1_791_000_000_000 + r),
            pairs=2, reps=4)
        assert "error" not in row, row
        for k in ("overhead_pct", "overhead_ok", "on_calls_per_s",
                  "off_calls_per_s", "pairs", "reps"):
            assert k in row, (k, row)
        assert isinstance(row["overhead_ok"], bool)
        assert row["on_calls_per_s"] > 0
        assert row["off_calls_per_s"] > 0
        assert row["pairs"] == 2 and row["reps"] == 4
        # the A/B restores the ledger state it toggled
        assert inst.memledger.enabled is True
    finally:
        inst.close()


def test_scenarios_section_child_writes_row(tmp_path):
    """The 15_scenarios row (ISSUE 16) through the driver's real child
    protocol: the whole committed spec library runs fast-mode with
    every oracle armed, and the row pins per-scenario verdicts (the
    bench-diff gate compares them by name) plus the judge-tap
    service-path A/B.

    The library's every-oracle verdicts are pinned individually (and
    strictly) by tests/test_scenarios.py; this test pins the child
    protocol and the row schema.  Because the child spins five real
    stack assemblies back-to-back, a loaded tier-1 host can starve a
    cluster's settle window — so a run that isn't all_ok gets ONE
    retry, and only a repeatable failure fails the build."""
    rows = _run_section("scenarios", tmp_path, timeout=600)
    r = rows["15_scenarios"]
    if not r["all_ok"]:
        rows = _run_section("scenarios", tmp_path, timeout=600)
        r = rows["15_scenarios"]
    assert r["count"] >= 7
    assert r["all_ok"] is True, {
        n: c for n, c in r["scenarios"].items() if not c["ok"]}
    assert len(r["scenarios"]) == r["count"]
    stacks = set()
    for name, cell in r["scenarios"].items():
        assert cell["ok"] is True, (name, cell)
        assert cell["error_rows"] == 0, (name, cell)
        assert cell["requests"] > 0
        assert len(cell["decision_digest"]) == 16
        assert cell["oracle_ok"] and all(
            isinstance(v, bool) for v in cell["oracle_ok"].values())
        stacks.add(cell["stack"])
    assert {"object", "wire", "clustered", "mesh", "tiered"} <= stacks
    ji = r["scenarios"]["tenant_abuse_9010"]["jain_index"]
    assert 0.0 < ji < 1.0
    ab = r["runner_ab"]
    assert "error" not in ab, ab
    for k in ("overhead_pct", "overhead_ok", "on_calls_per_s",
              "off_calls_per_s", "pairs", "reps", "rows"):
        assert k in ab, (k, ab)
    assert isinstance(ab["overhead_ok"], bool)


def test_scenario_ab_block_schema():
    """The 15_scenarios ``runner_ab`` block run directly on a small
    instance: schema + the JudgeTap's O(1) observe discipline (all
    per-row attribution deferred to finalize), same A/B pattern as
    ``memledger_ab``."""
    sys.path.insert(0, REPO)
    import bench
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.oracle import OracleEngine
    from gubernator_tpu.types import RateLimitRequest

    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        reqs = [RateLimitRequest(name="ab", unique_key=f"k{i}", hits=1,
                                 limit=1000, duration=60_000)
                for i in range(8)]
        row = bench._scenario_ab(inst, reqs, pairs=2, reps=4)
        assert "error" not in row, row
        for k in ("overhead_pct", "overhead_ok", "on_calls_per_s",
                  "off_calls_per_s", "pairs", "reps", "rows"):
            assert k in row, (k, row)
        assert isinstance(row["overhead_ok"], bool)
        assert row["on_calls_per_s"] > 0
        assert row["off_calls_per_s"] > 0
        assert row["pairs"] == 2 and row["reps"] == 4
        assert row["rows"] == 8
    finally:
        inst.close()


def test_fleet_section_child_writes_row(tmp_path):
    """The 16_fleet row (ISSUE 19) through the driver's real child
    protocol: the audit-tap A/B must land under its < 1% budget shape
    (schema pinned; the verdict bool is what bench-diff latches), and
    the 3-daemon fleet merge must measure a conserved steady state —
    drift exactly zero, tenant rollup sum-exact — with a finite merge
    wall time."""
    rows = _run_section("fleet", tmp_path, timeout=600)
    r = rows["16_fleet"]
    ab = r["audit_ab"]
    assert "error" not in ab, ab
    for k in ("overhead_pct", "overhead_ok", "on_calls_per_s",
              "off_calls_per_s", "pairs", "reps"):
        assert k in ab, (k, ab)
    assert isinstance(ab["overhead_ok"], bool)
    assert ab["on_calls_per_s"] > 0
    assert ab["off_calls_per_s"] > 0
    m = r["merge"]
    assert "error" not in m, m
    assert m["daemons"] == 3
    assert m["drift"] == 0
    assert m["conserved_ok"] is True
    assert m["tenants_sum_ok"] is True
    assert r["fleet_merge_wall_ms"] > 0


def test_audit_ab_block_schema():
    """The 16_fleet ``audit_ab`` block run directly on a small
    instance: schema + that the A/B restores the tap it toggled."""
    sys.path.insert(0, REPO)
    import bench
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.types import Behavior, RateLimitRequest

    # a real engine: the A/B drives the columnar GLOBAL wire lane,
    # which the pure-python OracleEngine reference lane doesn't serve
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0))
    try:
        reqs = [[RateLimitRequest(name="ab", unique_key=f"k{i}",
                                  hits=1, limit=1000,
                                  duration=86_400_000,
                                  behavior=Behavior.GLOBAL)
                 for i in range(4)]]
        datas = bench._serialize_reqs(reqs)
        row = bench._audit_ab(inst, datas, pairs=2, reps=4)
        assert "error" not in row, row
        for k in ("overhead_pct", "overhead_ok", "on_calls_per_s",
                  "off_calls_per_s", "pairs", "reps"):
            assert k in row, (k, row)
        assert isinstance(row["overhead_ok"], bool)
        assert row["on_calls_per_s"] > 0
        assert row["pairs"] == 2 and row["reps"] == 4
        # the A/B restores the tap it toggled
        assert inst.global_manager.audit is not None
    finally:
        inst.close()


def test_section_registry_covers_baseline_rows():
    """Every BASELINE row key the orchestrator may need to error-fill
    is declared by exactly one section."""
    sys.path.insert(0, REPO)
    import bench

    declared = [k for _, keys in bench._SECTIONS.values() for k in keys]
    assert len(declared) == len(set(declared)), "duplicate row keys"
    for row in ["1_single_key_smoke", "2_leaky_1k_keys",
                "4_global_sharded", "5_gregorian_churn",
                "6_service_path", "7_hot_psum", "8_peer_path",
                "9_clustered_service", "10_reuseport_group",
                "11_pallas_serving", "12_mesh_global",
                "13_tiered_store", "15_scenarios", "16_fleet"]:
        assert row in declared, row
    for name in bench._SECTION_ORDER:
        assert name in bench._SECTIONS


def test_flagship_defaults_are_the_round5_shape():
    """The driver runs `python bench.py` with NO env: the defaults ARE
    the flagship claim.  Round 5 moved it to CAP 2^26 / 8-probe (the
    16-probe window triggers the serialized scatter lowering at CAP >=
    2^25 on 2026-08 backend builds, while 2^26/8-probe is zero-loss for
    the 10M-key populate — BASELINE.md round-5 table).  Import in a
    child: bench's module-level env defaults must not leak here."""
    code = (
        "import os, json\n"
        "import bench\n"
        "print(json.dumps({'cap': bench.CAP, 'n_keys': bench.N_KEYS,\n"
        "    'probes_env': os.environ.get('GUBER_PROBES', '')}))\n"
    )
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("GUBER_")}
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       timeout=120, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE)
    assert r.returncode == 0, r.stderr.decode()[-500:]
    got = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert got["cap"] == 1 << 26, got
    assert got["n_keys"] == 10_000_000, got
    # bench must NOT export a probe override anymore: the serving
    # default (core/step.py PROBES == 8) is the flagship window
    assert got["probes_env"] == "", got


def test_lint_clean_and_compile_ledger_provenance_schema():
    """The ``extra.lint_clean`` provenance block (ISSUE 14): pin its
    schema — clean flag, pass/violation counts, and the compile-ledger
    verdict whose shape row 6_service_path's ``compile_ledger`` block
    shares (both come from CompileLedger.verdict())."""
    sys.path.insert(0, REPO)
    import bench
    from tools.guberlint import PASS_NAMES

    block = bench._lint_clean()
    assert block is not None, "lint probe failed entirely"
    assert set(block) == {"clean", "passes", "violations",
                          "compile_ledger"}
    assert block["clean"] is True and block["violations"] == 0
    assert block["passes"] == len(PASS_NAMES) == 9
    cl = block["compile_ledger"]
    assert cl is not None, "compile ledger probe failed"
    assert set(cl) == {"enabled", "installed", "marked_steady",
                       "total_compiles", "steady_recompiles", "steady"}
    assert isinstance(cl["steady_recompiles"], dict)
    assert isinstance(cl["steady"], bool)

"""The bench-diff gate's scenario rules (ISSUE 16): the 15_scenarios
row surfaces per-scenario verdict bools and Jain's fairness indexes to
``tools/bench_compare.py``, which must flag a verdict flip or a
fairness drift beyond the absolute tolerance by scenario NAME — and
stay quiet inside the band.
"""
import copy

from tools.bench_compare import JAIN_TOL, _numeric_metrics, compare


def _scen_row(jain=0.20, ok=True, cons_ok=True):
    return {
        "count": 2, "all_ok": ok and cons_ok,
        "runner_ab": {"overhead_pct": 0.4, "overhead_ok": True},
        "scenarios": {
            "tenant_abuse_9010": {
                "ok": ok, "stack": "object", "requests": 200,
                "error_rows": 0, "decision_digest": "ab" * 8,
                "oracle_ok": {"fairness": ok, "parity": True},
                "jain_index": jain},
            "partition_reconcile": {
                "ok": cons_ok, "stack": "clustered", "requests": 100,
                "error_rows": 0, "decision_digest": "cd" * 8,
                "oracle_ok": {"conservation": cons_ok}}}}


def _rows(**kw):
    return {"15_scenarios": _scen_row(**kw)}


def test_numeric_metrics_surfaces_scenario_cells():
    m = _numeric_metrics(_scen_row(), "15_scenarios")
    assert m["scenarios.tenant_abuse_9010.ok"] is True
    assert m["scenarios.tenant_abuse_9010.oracle_ok.fairness"] is True
    assert m["scenarios.tenant_abuse_9010.jain_index"] == 0.20
    assert m["scenarios.partition_reconcile.oracle_ok.conservation"] \
        is True
    assert m["all_ok"] is True
    # per-scenario keys only appear for the scenarios row
    plain = _numeric_metrics(_scen_row(), "6_service_path")
    assert not any(k.startswith("scenarios.") for k in plain)


def test_verdict_flip_is_a_regression_by_name():
    verdict = compare(_rows(), _rows(ok=False))
    names = {r["metric"] for r in verdict["regressions"]}
    assert "scenarios.tenant_abuse_9010.ok" in names
    assert "scenarios.tenant_abuse_9010.oracle_ok.fairness" in names
    assert "all_ok" in names
    # the untouched scenario stays clean
    assert not any("partition_reconcile" in n for n in names)


def test_oracle_flip_alone_is_caught():
    verdict = compare(_rows(), _rows(cons_ok=False))
    names = {r["metric"] for r in verdict["regressions"]}
    assert "scenarios.partition_reconcile.oracle_ok.conservation" \
        in names


def test_false_to_true_is_not_a_regression():
    verdict = compare(_rows(ok=False), _rows(ok=True))
    assert verdict["regressions"] == []


def test_jain_drift_beyond_tolerance_regresses_both_directions():
    for new in (0.20 + JAIN_TOL + 0.01, 0.20 - JAIN_TOL - 0.01):
        verdict = compare(_rows(jain=0.20), _rows(jain=new))
        hits = [r for r in verdict["regressions"]
                if r["metric"] == "scenarios.tenant_abuse_9010"
                                  ".jain_index"]
        assert len(hits) == 1, (new, verdict["regressions"])
        assert hits[0]["tolerance"] == JAIN_TOL
        assert "fairness" in hits[0]["why"]


def test_jain_drift_within_tolerance_passes():
    for new in (0.20 + JAIN_TOL - 0.01, 0.20 - JAIN_TOL + 0.01, 0.20):
        verdict = compare(_rows(jain=0.20), _rows(jain=new))
        assert verdict["regressions"] == [], new


def test_scenario_added_or_removed_is_not_compared():
    """A new scenario in the library (or one retired) has no
    counterpart — the gate diffs the intersection only."""
    old = _rows()
    new = copy.deepcopy(old)
    cell = new["15_scenarios"]["scenarios"].pop("partition_reconcile")
    new["15_scenarios"]["scenarios"]["fresh_spec"] = cell
    verdict = compare(old, new)
    assert verdict["regressions"] == []
    assert verdict["compared_metrics"] > 0


def test_skipped_row_shortcircuits_scenarios_too():
    old, new = _rows(), _rows(ok=False)
    new["15_scenarios"]["context"] = "host was swapping"
    verdict = compare(old, new)
    assert verdict["regressions"] == []
    assert verdict["skipped_rows"] == [
        {"row": "15_scenarios", "reason": "context"}]

"""Multi-host bootstrap: two real OS processes form one JAX cluster
(CPU devices standing in for two hosts' chips) and run the decision
step + a psum fold across the process boundary — the DCN-analog of the
pod-local collectives (SURVEY.md §5.8)."""
import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1]); coord = sys.argv[2]
os.environ.pop("JAX_PLATFORMS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from gubernator_tpu.parallel import multihost

multihost.initialize(coord, num_processes=2, process_id=proc_id,
                     local_device_count=2)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()  # 2 hosts x 2 devices

import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

mesh = multihost.global_mesh()

# per-"chip" hot-set style consumption fold across the process boundary
def fold(d):
    return lax.psum(d, "shard")

folded = jax.jit(shard_map(fold, mesh=mesh, in_specs=P("shard"),
                           out_specs=P()))
local = np.full((2, 8), proc_id + 1, np.int64)  # this host's 2 shards
d = multihost.process_local_batch(mesh, local, (4, 8))
out = folded(d)
got = np.asarray(jax.device_get(
    out.addressable_shards[0].data)).reshape(-1)
# psum over shards: 1 + 1 + 2 + 2 = 6 everywhere
assert (got == 6).all(), got

# the decision step compiles and runs over the multi-host mesh
from gubernator_tpu.core.batch import pack_requests
from gubernator_tpu.parallel.mesh import shard_table
from gubernator_tpu.parallel.sharded import make_sharded_step
from gubernator_tpu.types import RateLimitRequest

step = make_sharded_step(mesh)
state = shard_table(mesh, 1 << 8)
B = 16  # per shard
reqs = [RateLimitRequest(name="mh", unique_key=f"k{proc_id}_{i}", hits=1,
                         limit=5, duration=60_000) for i in range(2 * B)]
batch, _ = pack_requests(reqs, 1_760_000_000_000, size=2 * B)
from jax.sharding import NamedSharding
sh = NamedSharding(mesh, P("shard"))
import jax.numpy as jnp2
dev_batch = type(batch)(*[
    multihost.process_local_batch(mesh, np.asarray(c),
                                  (4 * B,) + np.asarray(c).shape[1:])
    for c in batch])
state, outs, counters = step(state, dev_batch,
                             jnp.asarray(1_760_000_000_000, jnp.int64))
over, ins = int(counters[0]), int(counters[1])
assert ins == 4 * B // 2 * 2, ins  # every process's 2B keys inserted

# the pallas (Mosaic-kernel) serving step over the SAME multi-host
# mesh — the kernel mode's DCN-analog gate (interpret on CPU, same as
# its off-TPU serving path).  Raw packed lanes: the engine's host
# routing is single-process, but the device step is pure shard_map.
from gubernator_tpu.ops import pallas_step as pstep_mod
from gubernator_tpu.parallel.pallas_engine import make_pallas_step_packed

CAPL = 1 << 8   # rows per shard
PB = 32         # batch rows per shard
pkstep = make_pallas_step_packed(mesh, interpret=True)
rows = multihost.process_local_batch(
    mesh, np.zeros((2 * CAPL, pstep_mod.WORDS), np.int32),
    (4 * CAPL, pstep_mod.WORDS))
NOWP = 1_760_000_000_000
rngp = np.random.default_rng(100 + proc_id)
nreq = 2 * PB
alg = np.zeros(nreq, np.int32)
alg[::2] = 1  # half LEAKY
from gubernator_tpu.core.batch import RequestBatch as RB
from gubernator_tpu.parallel.sharded import pack_wave_host

pbatch = RB(
    key=rngp.integers(1, 1 << 62, nreq).astype(np.uint64),
    hits=np.ones(nreq, np.int64),
    limit=np.full(nreq, 5, np.int64),
    duration=np.full(nreq, 60_000, np.int64),
    eff_ms=np.full(nreq, 60_000, np.int64),
    greg_end=np.zeros(nreq, np.int64),
    behavior=np.zeros(nreq, np.int32), algorithm=alg,
    burst=np.full(nreq, 5, np.int64),
    valid=np.ones(nreq, bool),
    now=np.full(nreq, NOWP, np.int64))
a64_host, a32_host = pack_wave_host(pbatch)
a64 = multihost.process_local_batch(mesh, a64_host, (8, 4 * PB),
                                    spec=P(None, "shard"))
a32 = multihost.process_local_batch(mesh, a32_host, (3, 4 * PB),
                                    spec=P(None, "shard"))
rows, packed, (pover, pins) = pkstep(
    rows, a64, a32, jnp.asarray(NOWP, jnp.int64))
assert int(pins) == 4 * PB, int(pins)  # every key inserted, all shards
st_local = np.asarray(jax.device_get(
    packed.addressable_shards[0].data))
assert (st_local[0] == 0).all()        # fresh keys: UNDER_LIMIT
assert (st_local[1] == 4).all()        # remaining = 5 - 1

print(f"proc {proc_id} ok: psum fold + sharded step over 2 hosts, "
      f"inserted={ins}, pallas inserted={int(pins)}")
"""


@pytest.mark.skipif(os.environ.get("GUBER_SKIP_MULTIHOST") == "1",
                    reason="multihost test disabled")
def test_two_process_cluster_runs_step_and_fold(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode())
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} ok" in out

"""Clustered wire fast lane (VERDICT r1 item 4): client-facing
GetRateLimits must take the columnar lane end-to-end in a multi-daemon
cluster — C++ parse → ring split by owner → raw-TLV forwards over the
peer wire → ordered response splice — with oracle parity.

Round 1's lane required `not self.peers()` (instance.py), so every real
cluster fell back to per-request pb2 objects on the client path; these
tests pin the fix.
"""
import time

import numpy as np
import pytest

from gubernator_tpu import Algorithm, Behavior, Oracle, RateLimitRequest
from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.peers import ConsistentHash, ReplicatedConsistentHash
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import PeerInfo

HOUR = 3_600_000
DAY = 24 * HOUR


def clock_ms() -> int:
    return int(time.time() * 1000)


def serialize(reqs):
    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        m.name = r.name
        m.unique_key = r.unique_key
        m.hits = r.hits
        m.limit = r.limit
        m.duration = r.duration
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
        m.burst = r.burst
    return msg.SerializeToString()


def lane_count(inst, lane: str) -> float:
    return inst.metrics.wire_lane_counter.labels(lane=lane)._value.get()


def mk_wave(w: int):
    """Mixed token/leaky requests over many keys incl. in-batch
    duplicates; durations long enough that wall-clock skew between
    daemons cannot move a token boundary during the test."""
    reqs = []
    for i in range(40):
        reqs.append(RateLimitRequest(
            name="wcl", unique_key=f"t{i}", hits=1 + (i + w) % 3, limit=9,
            duration=DAY, algorithm=Algorithm.TOKEN_BUCKET))
    for i in range(12):
        reqs.append(RateLimitRequest(
            name="wcl", unique_key=f"l{i}", hits=2, limit=40,
            duration=DAY, algorithm=Algorithm.LEAKY_BUCKET, burst=12))
    # duplicates of a few keys inside the same batch (segment semantics
    # must survive the forward/merge round trip)
    for i in range(6):
        reqs.append(RateLimitRequest(
            name="wcl", unique_key=f"t{i}", hits=2, limit=9,
            duration=DAY, algorithm=Algorithm.TOKEN_BUCKET))
    return reqs


class TestClusteredWireLane:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = cluster_mod.start(3)
        yield c
        c.stop()

    def test_oracle_parity_and_lane(self, cluster):
        inst = cluster.instance_at(0)
        oracle = Oracle()
        before = lane_count(inst, "wire_clustered")
        fallback_before = lane_count(inst, "pb2_fallback")
        for w in range(4):
            reqs = mk_wave(w)
            now = clock_ms()
            want = oracle.check_batch(reqs, now)
            out = pb.GetRateLimitsResp.FromString(
                inst.get_rate_limits_wire(serialize(reqs), now_ms=now))
            assert len(out.responses) == len(reqs)
            for i, (g, e) in enumerate(zip(out.responses, want)):
                assert g.error == "", (w, i, g.error)
                assert (int(g.status), int(g.remaining), int(g.limit)) == \
                    (int(e.status), int(e.remaining), int(e.limit)), \
                    (w, i, reqs[i])
                # forwarded keys are served on the owner's clock; allow
                # wall-clock skew but not truncation
                assert abs(int(g.reset_time) - int(e.reset_time)) < 60_000
        n_total = 4 * len(mk_wave(0))
        assert lane_count(inst, "wire_clustered") - before == n_total
        assert lane_count(inst, "pb2_fallback") == fallback_before
        # at least one owner actually served forwarded columns over the
        # peer wire lane (keys spread across 3 daemons)
        peer_wire = sum(lane_count(cluster.instance_at(i), "peer_wire")
                        for i in range(3))
        assert peer_wire > 0

    def test_remote_over_limit_counted_and_consistent(self, cluster):
        """One key hammered through daemon 0 must enforce its limit
        exactly once cluster-wide regardless of which daemon owns it."""
        inst = cluster.instance_at(0)
        key = "hammer"
        reqs = [RateLimitRequest(name="wcl2", unique_key=key, hits=1,
                                 limit=5, duration=DAY)] * 8
        now = clock_ms()
        out = pb.GetRateLimitsResp.FromString(
            inst.get_rate_limits_wire(serialize(reqs), now_ms=now))
        statuses = [int(r.status) for r in out.responses]
        assert statuses == [0] * 5 + [1] * 3
        remaining = [int(r.remaining) for r in out.responses]
        assert remaining[:5] == [4, 3, 2, 1, 0]

    def test_dead_peer_degrades_per_subbatch(self, cluster):
        """Requests owned by a dead peer serve DEGRADED from the local
        shard (ISSUE 5): flagged success rows, never error rows;
        everything else is untouched.  The raw error-row semantics
        underneath the fallback stay pinned by test_peer_fastpath's
        death test (peer_degraded_fallback=False)."""
        inst = cluster.instance_at(0)
        # find keys owned by daemon 2 vs daemon 0
        owned2, owned_other = [], []
        for i in range(200):
            k = f"dp{i}"
            d = cluster.owner_daemon_of("wcl3_" + k)
            (owned2 if d is cluster.daemon_at(2) else owned_other).append(k)
            if len(owned2) >= 5 and len(owned_other) >= 5:
                break
        assert owned2 and owned_other
        cluster.daemon_at(2).close()
        try:
            reqs = [RateLimitRequest(name="wcl3", unique_key=k, hits=1,
                                     limit=10, duration=DAY)
                    for k in owned2[:5] + owned_other[:5]]
            out = pb.GetRateLimitsResp.FromString(
                inst.get_rate_limits_wire(serialize(reqs),
                                          now_ms=clock_ms()))
            by_key = dict(zip(owned2[:5] + owned_other[:5], out.responses))
            for k in owned2[:5]:
                r = by_key[k]
                assert r.error == ""
                assert r.metadata["degraded"] == "true"
                # answered from daemon 0's own (empty) shard
                assert int(r.remaining) == 9
            for k in owned_other[:5]:
                assert by_key[k].error == ""
                assert "degraded" not in by_key[k].metadata
                assert int(by_key[k].remaining) == 9
            assert inst.metrics.degraded_served.labels(
                peer_addr=cluster.peer_at(2).grpc_address
            )._value.get() >= 5
        finally:
            # restore daemon 2 for any later test using the fixture
            cluster.restart(2)


class TestOwnerIndices:
    """owner_indices must agree bit-for-bit with get()/get_by_hash."""

    class _Peer:
        def __init__(self, addr):
            self.info = PeerInfo(grpc_address=addr)

    @pytest.mark.parametrize("picker_cls",
                             [ConsistentHash, ReplicatedConsistentHash])
    def test_matches_scalar(self, picker_cls):
        picker = picker_cls()
        for i in range(5):
            picker.add(self._Peer(f"10.0.0.{i}:81"))
        rng = np.random.default_rng(7)
        hashes = rng.integers(0, 2**64, size=4096, dtype=np.uint64)
        idx = picker.owner_indices(hashes)
        peers = picker.owner_peers()
        for h, j in zip(hashes.tolist(), idx.tolist()):
            assert picker.get_by_hash(h) is peers[j]

    def test_ring_edges(self):
        picker = ReplicatedConsistentHash()
        for i in range(3):
            picker.add(self._Peer(f"10.0.0.{i}:81"))
        edge = np.array([0, 1, 2**64 - 1, picker._ring[0],
                         picker._ring[-1]], dtype=np.uint64)
        idx = picker.owner_indices(edge)
        peers = picker.owner_peers()
        for h, j in zip(edge.tolist(), idx.tolist()):
            assert picker.get_by_hash(h) is peers[j]


class TestWorkerOnlyIngest:
    """Heterogeneous front-door shape (ARCHITECTURE.md §3.1): a daemon
    whose ring omits itself owns NO keys and forwards every request to
    the owners — the ingest-worker role on a TPU host, where CPU
    workers absorb the parse/split/assembly GIL cost and the single
    device-owner daemon pays only the columnar peer-apply."""

    @pytest.fixture(scope="class")
    def pair(self):
        c = cluster_mod.start(2)
        owner, worker = c.daemon_at(0), c.daemon_at(1)
        # worker's ring lists only the owner; owner serves solo
        owner.set_peers([owner.peer_info()])
        worker.set_peers([owner.peer_info()])
        yield c
        c.stop()

    def test_worker_forwards_everything_with_parity(self, pair):
        owner, worker = pair.instance_at(0), pair.instance_at(1)
        oracle = Oracle()
        peer_before = lane_count(owner, "peer_wire")
        lane_before = lane_count(worker, "wire_clustered")
        for w in range(2):
            reqs = mk_wave(w)
            now = clock_ms()
            want = oracle.check_batch(reqs, now)
            out = pb.GetRateLimitsResp.FromString(
                worker.get_rate_limits_wire(serialize(reqs), now_ms=now))
            assert len(out.responses) == len(reqs)
            for i, (g, e) in enumerate(zip(out.responses, want)):
                assert g.error == "", (w, i, g.error)
                assert (int(g.status), int(g.remaining), int(g.limit)) == \
                    (int(e.status), int(e.remaining), int(e.limit)), \
                    (w, i, reqs[i])
        n_total = 2 * len(mk_wave(0))
        # worker still rides the columnar clustered lane...
        assert lane_count(worker, "wire_clustered") - lane_before == n_total
        # ...and owns nothing: every decision crossed the peer wire
        assert lane_count(owner, "peer_wire") - peer_before == n_total

    def test_bucket_shared_between_worker_and_owner_entry(self, pair):
        """The same key drained through the worker and directly at the
        owner must hit one shared bucket (ownership is ring-global)."""
        owner, worker = pair.instance_at(0), pair.instance_at(1)
        now = clock_ms()

        def one(hits):
            return serialize([RateLimitRequest(
                name="wo", unique_key="shared", hits=hits, limit=10,
                duration=DAY)])

        r1 = pb.GetRateLimitsResp.FromString(
            worker.get_rate_limits_wire(one(4), now_ms=now))
        r2 = pb.GetRateLimitsResp.FromString(
            owner.get_rate_limits_wire(one(4), now_ms=now))
        assert int(r1.responses[0].remaining) == 6
        assert int(r2.responses[0].remaining) == 2

"""Tiered key store (ISSUE 10): HBM hot tier + host cold tier with
sketch-driven admission.

The acceptance battery: a device table capped far below the key domain
must serve every request EXACTLY — table-full stops being an error row
and becomes a cold-tier find-or-create — with decisions byte-identical
to an uncapped single-tier engine on the same traffic.  Covered lanes:
the classic blocking engine, the pipelined launch/sync split, the
fused serving engine, the mesh-GLOBAL replica tier's cap-overflow
demote, the two-tier snapshot/restore round trip, a 16-thread unwarmed
churn with exact conservation as the oracle, and native-vs-dict cold
store parity."""
import random
import threading

import numpy as np
import pytest

from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.core.batch import pack_columns
from gubernator_tpu.hashing import hash_key
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.parallel.sharded import ShardedEngine
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.store import MockLoader
from gubernator_tpu.tiering import ROW_COLS, TierController, _make_store
from gubernator_tpu.types import Behavior, RateLimitRequest

NOW = 1_790_000_000_000
DAY = 86_400_000
LIMIT = 10 ** 6


def _packed(keys, hits, now):
    kh = np.array([hash_key("tier", f"k{k}") for k in keys], np.uint64)
    n = len(keys)
    b, errs = pack_columns(kh, np.asarray(hits, np.int64),
                           np.full(n, 1000, np.int64),
                           np.full(n, DAY, np.int64),
                           np.zeros(n, np.int64), np.zeros(n, np.int64),
                           np.zeros(n, np.int64), now)
    assert not errs
    return b, kh


def _engine_pair(capped_cls=ShardedEngine, threshold=4):
    """64-row tiered engine + 16K-row uncapped control, same mesh."""
    mesh = make_mesh(n=1)
    ranks: dict = {}
    small = capped_cls(mesh, capacity_per_shard=64, batch_per_shard=64)
    big = ShardedEngine(mesh, capacity_per_shard=1 << 14,
                        batch_per_shard=64)
    tc = TierController(small, rank_fn=lambda kh: ranks.get(kh, 0),
                        promote_threshold=threshold)
    return small, big, tc, ranks


def _assert_wave_parity(r_tier, r_ctl, step):
    assert not np.asarray(r_tier[4]).any(), \
        f"step {step}: table-full rows leaked through the tier"
    for a, c, nm in zip(r_tier[:4], r_ctl[:4],
                        ("status", "limit", "remaining", "reset")):
        a, c = np.asarray(a), np.asarray(c)
        assert (a == c).all(), \
            (step, nm, np.nonzero(a != c)[0][:5].tolist())


def _audit_all_rows(small, big, tc, nkeys):
    """Every live control row exists in exactly one tier, bit-equal."""
    allk = np.array(sorted({hash_key("tier", f"k{k}")
                            for k in range(1, nkeys)}), np.uint64)
    f2, c2 = big.gather_rows(allk)
    f1, c1 = small.gather_rows(allk)
    for i in np.nonzero(f2)[0]:
        want = tuple(int(c2[f][i]) for f in ROW_COLS)
        if f1[i]:
            assert tc.peek_row(int(allk[i])) is None, \
                f"key {allk[i]} in BOTH tiers"
            got = tuple(int(c1[f][i]) for f in ROW_COLS)
        else:
            cold = tc.peek_row(int(allk[i]))
            assert cold is not None, f"key {allk[i]} lost from both tiers"
            got = tuple(cold[f] for f in ROW_COLS)
        assert got == want, (int(allk[i]), got, want)


def _drive_parity(small, big, tc, ranks, *, steps=50, nkeys=2000,
                  pipelined=False, seed=5):
    rng = random.Random(seed)
    lock = threading.Lock()
    for step in range(steps):
        keys = [rng.randrange(1, nkeys) for _ in range(50)]
        hits = [rng.choice((0, 1, 2, 5)) for _ in keys]
        now = NOW + step * 1000
        b, kh = _packed(keys, hits, now)
        for k in kh:
            ranks[int(k)] = ranks.get(int(k), 0) + 1
        if pipelined:
            tok = small.launch_packed(b, kh, now)
            r1 = small.sync_packed(tok, engine_lock=lock)
        else:
            r1 = small.check_packed(b, kh, now)
        r2 = big.check_packed(b, kh, now)
        _assert_wave_parity(r1, r2, step)
    st = tc.stats()
    assert st["promotions"] > 0 and st["demotions"] > 0, \
        f"no migration traffic: {st}"
    assert st["cold_served"] > 0 and st["cold_keys"] > 0
    _audit_all_rows(small, big, tc, nkeys)
    return st


def test_engine_capped_parity_and_migration():
    """Tentpole acceptance at engine level: 2000 keys through a 64-row
    table + cold tier are byte-identical to a 16K-row table, zero
    table-full rows, with real promote/demote traffic, and every row
    lives in exactly one tier afterwards."""
    small, big, tc, ranks = _engine_pair()
    _drive_parity(small, big, tc, ranks)


def test_pipelined_lane_cold_serve_parity():
    """The launch/sync split lane: cold rows ride the wave invalid and
    re-dispatch exactly at sync time (under the engine lock), so the
    pipelined dispatcher path keeps the same byte-identical contract."""
    small, big, tc, ranks = _engine_pair()
    _drive_parity(small, big, tc, ranks, pipelined=True, seed=6)


def test_fused_engine_overflow_parity():
    """Satellite: the fused serving engine (one device program per
    wave) routes its bucket-full rows through the same cold lane — its
    inherited resolve must match the classic engine byte-for-byte."""
    pallas_engine = pytest.importorskip(
        "gubernator_tpu.parallel.pallas_engine")
    small, big, tc, ranks = _engine_pair(
        capped_cls=pallas_engine.XlaFusedEngine)
    _drive_parity(small, big, tc, ranks, steps=40, seed=7)


def _seed_rank(inst, kh, weight):
    """Deterministically give ``kh`` sketch rank ``weight`` (the tap
    feed is async; tests must not sleep-and-hope)."""
    a = inst.analytics
    with a._mu:
        a.sketch.update(np.array([kh], np.uint64),
                        np.array([weight], np.int64),
                        np.zeros(1, bool), NOW)


def _greq(key, hits=1, name="mg", behavior=Behavior.GLOBAL):
    return RateLimitRequest(name=name, unique_key=key, hits=hits,
                            limit=LIMIT, duration=DAY, behavior=behavior)


def test_mesh_global_overflow_demotes(monkeypatch):
    """Satellite: a mesh-GLOBAL pin hitting a full probe window admits
    by sketch rank — the coldest occupant is demoted through the exact
    stand-down migration (its consumed hits land in the sharded row),
    the newcomer pins, and the overflow leaves a flight-recorder
    event."""
    monkeypatch.setenv("GUBER_MESH_GLOBAL_CAP", "16")
    inst = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0,
                             global_mode="mesh", batch_rows=64,
                             behaviors=BehaviorConfig(
                                 global_sync_wait_ms=100)),
                      mesh=make_mesh(n=4))
    try:
        fill = {f"g{i}": hash_key("mg", f"g{i}") for i in range(64)}
        r = inst.get_rate_limits([_greq(k) for k in fill],
                                 now_ms=NOW + 1)
        assert all(x.error == "" for x in r)
        mge = inst._meshglobal
        assert mge is not None
        pinned = {k: kh for k, kh in fill.items() if mge.is_pinned(kh)}
        assert len(pinned) >= 8, "fill never saturated the 16-slot tier"
        # a newcomer whose whole probe window is occupied — the pin
        # that MUST overflow instead of silently declining
        occ = set(mge.slots.values())
        hot = next(f"h{c}" for c in range(500)
                   if all(s in occ for s in
                          mge._probe_slots_host(hash_key("mg",
                                                         f"h{c}"))))
        hot_kh = hash_key("mg", hot)
        _seed_rank(inst, hot_kh, 100)
        r = inst.get_rate_limits([_greq(hot)], now_ms=NOW + 2)
        assert r[0].error == ""
        assert mge.is_pinned(hot_kh), "hot newcomer was not admitted"
        evs = inst.recorder.events(kind="mesh_overflow_demote")
        ev = next(e for e in reversed(evs)
                  if int(e["admitted"]) == hot_kh)
        victim_kh = int(ev["khash"])
        assert not mge.is_pinned(victim_kh)
        victim_key = next(k for k, kh in fill.items()
                          if kh == victim_kh)
        # the demoted row must carry its consumed hit — a fresh-row
        # re-create here would read LIMIT and break conservation
        q = inst.get_rate_limits([_greq(victim_key, hits=0,
                                        behavior=Behavior(0))],
                                 now_ms=NOW + 3)
        assert q[0].error == ""
        assert q[0].remaining == LIMIT - 1, \
            f"demoted row lost its hit: remaining={q[0].remaining}"
    finally:
        inst.close()


def _tier_cfg(**kw):
    d = dict(cache_size=1024, cache_autogrow_max=1024, tier_cold=True,
             tier_promote_threshold=2, sweep_interval_ms=0,
             behaviors=BehaviorConfig())
    d.update(kw)
    return Config(**d)


def _fill_keys(inst, prefix, n, now, hits=0, name="tier", chunk=512):
    for base in range(0, n, chunk):
        reqs = [RateLimitRequest(name=name, unique_key=f"{prefix}{i}",
                                 hits=hits, limit=LIMIT, duration=DAY)
                for i in range(base, min(base + chunk, n))]
        for resp in inst.get_rate_limits(reqs, now_ms=now):
            assert resp.error == ""


def _live_rows(inst):
    """{khash: row-tuple} across BOTH tiers; asserts no key in both."""
    rows = {}
    arrays = inst.engine.snapshot()
    for i in range(len(arrays["key"])):
        rows[int(arrays["key"][i])] = tuple(int(arrays[f][i])
                                            for f in ROW_COLS)
    cold = inst._tier.snapshot_arrays()
    ncold = 0
    if cold is not None:
        ncold = len(cold["key"])
        for i in range(ncold):
            kh = int(cold["key"][i])
            assert kh not in rows, f"key {kh} present in BOTH tiers"
            rows[kh] = tuple(int(cold[f][i]) for f in ROW_COLS)
    return rows, ncold


def test_two_tier_snapshot_roundtrip():
    """Satellite: Loader snapshot covers BOTH tiers and restore places
    every row back into exactly one tier — byte-exact, no phantom rows,
    no dropped rows."""
    loader = MockLoader()
    inst = V1Instance(_tier_cfg(loader=loader), mesh=make_mesh(n=1))
    try:
        assert inst._tier is not None
        _fill_keys(inst, "s", 3000, NOW, hits=1)
        before, ncold = _live_rows(inst)
        assert ncold > 0, "fill never spilled into the cold tier"
    finally:
        inst.close()
    assert loader.called["save"] == 1
    assert len(loader.contents) == len(before), \
        "snapshot dropped or invented rows"
    inst2 = V1Instance(_tier_cfg(loader=loader), mesh=make_mesh(n=1))
    try:
        after, ncold2 = _live_rows(inst2)
        assert after == before, "restore is not byte-exact"
        assert ncold2 > 0, "restore overflow rows did not land cold"
    finally:
        inst2.close()


def _ser(reqs):
    m = pb.GetRateLimitsReq()
    for r in reqs:
        q = m.requests.add()
        q.name, q.unique_key = r.name, r.unique_key
        q.hits, q.limit, q.duration = r.hits, r.limit, r.duration
        q.behavior = int(r.behavior)
        q.algorithm = int(r.algorithm)
    return m.SerializeToString()


def test_tier_chaos_16_threads_unwarmed():
    """Satellite: 16 threads hammer brand-new keys through BOTH wire
    and object lanes against a saturated 1024-row table — every key
    lands cold first, some migrate mid-race, and the oracle is exact
    conservation: every hit sent is debited exactly once."""
    inst = V1Instance(_tier_cfg(), mesh=make_mesh(n=1))
    try:
        assert inst._tier is not None
        _fill_keys(inst, "pad", 2048, NOW)  # saturate the device table
        nkeys, reps, threads, hits = 64, 8, 16, 2
        keys = [f"race{i}" for i in range(nkeys)]
        errs: list = []
        barrier = threading.Barrier(threads)

        def worker(t):
            try:
                barrier.wait(timeout=60)
                for r in range(reps):
                    req = RateLimitRequest(
                        name="tier",
                        unique_key=keys[(t * reps + r) % nkeys],
                        hits=hits, limit=LIMIT, duration=DAY)
                    if t % 2:
                        out = pb.GetRateLimitsResp.FromString(
                            inst.get_rate_limits_wire(
                                _ser([req]), now_ms=NOW + 1 + r))
                        if out.responses[0].error:
                            raise RuntimeError(out.responses[0].error)
                    else:
                        resp = inst.get_rate_limits(
                            [req], now_ms=NOW + 1 + r)
                        if resp[0].error:
                            raise RuntimeError(resp[0].error)
            except Exception as e:  # noqa: BLE001 - audited below
                errs.append(repr(e))

        ths = [threading.Thread(target=worker, args=(t,))
               for t in range(threads)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=120)
        assert not any(th.is_alive() for th in ths), "stuck threads"
        assert not errs, errs[:3]
        # force deterministic migration traffic: rank up a few keys
        # that are cold RIGHT NOW, then touch them (the async sketch
        # feed may or may not have promoted anyone during the race)
        cold_now = [k for k in keys
                    if inst._tier.peek_row(hash_key("tier", k))][:4]
        for k in cold_now:
            _seed_rank(inst, hash_key("tier", k), 50)
        if cold_now:
            reqs = [RateLimitRequest(name="tier", unique_key=k, hits=0,
                                     limit=LIMIT, duration=DAY)
                    for k in cold_now]
            for r in inst.get_rate_limits(reqs, now_ms=NOW + 100):
                assert r.error == ""
        st = inst._tier.stats()
        assert st["promotions"] + st["demotions"] > 0, st
        # exact conservation, cluster of one: sent == debited
        reqs = [RateLimitRequest(name="tier", unique_key=k, hits=0,
                                 limit=LIMIT, duration=DAY)
                for k in keys]
        debited = 0
        for r in inst.get_rate_limits(reqs, now_ms=NOW + 200):
            assert r.error == ""
            debited += LIMIT - r.remaining
        assert debited == threads * reps * hits, \
            f"lost hits: sent={threads * reps * hits} debited={debited}"
    finally:
        inst.close()


def test_cold_store_native_dict_parity(monkeypatch):
    """The native open-addressed cold table and the pure-Python dict
    reference agree on every operation, through growth and tombstone
    churn."""
    native = _make_store()
    if not native.native:
        pytest.skip("native cold_* primitives not built")
    monkeypatch.setenv("GUBER_TIER_NATIVE", "0")
    ref = _make_store()
    assert not ref.native
    rng = random.Random(3)
    keys = [rng.randrange(1, 1 << 62) for _ in range(3000)]
    for i, kh in enumerate(keys):
        row = tuple(i * 8 + j for j in range(len(ROW_COLS)))
        native.put(kh, row)
        ref.put(kh, row)
        probe = keys[rng.randrange(0, i + 1)]
        assert native.get(probe) == ref.get(probe)
        if i % 4 == 0:
            victim = keys[rng.randrange(0, i + 1)]
            assert native.pop(victim) == ref.pop(victim)
    assert len(native) == len(ref)
    arr = np.array(keys[:512] + [9_999_999_999], np.uint64)
    assert (native.contains_batch(arr) == ref.contains_batch(arr)).all()
    k1, r1 = native.snapshot()
    k2, r2 = ref.snapshot()
    s1 = {int(k): tuple(map(int, r)) for k, r in zip(k1, r1)}
    s2 = {int(k): tuple(map(int, r)) for k, r in zip(k2, r2)}
    assert s1 == s2

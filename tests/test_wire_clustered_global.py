"""Clustered GLOBAL through the columnar wire lane (VERDICT r2 item 3).

Round 2's lane demoted any clustered batch containing a GLOBAL row to
the pb2 object path — the hottest production shape (GLOBAL keys on a
multi-peer ring) was the one that lost the C++ lane.  These tests pin
the fix: GLOBAL rows ride `wire_clustered` (answered from the local
replica, per global.go semantics — SURVEY §3.3), their async reconcile
is queued as raw TLV prototypes (no per-request objects on the request
path), and the owner/replica convergence matches the object path's.
"""
import time

import pytest

from gubernator_tpu import Algorithm, Behavior, Oracle, RateLimitRequest
from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.proto import gubernator_pb2 as pb

DAY = 24 * 3_600_000


def clock_ms() -> int:
    return int(time.time() * 1000)


def serialize(reqs):
    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        m.name = r.name
        m.unique_key = r.unique_key
        m.hits = r.hits
        m.limit = r.limit
        m.duration = r.duration
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
        m.burst = r.burst
    return msg.SerializeToString()


def lane_count(inst, lane: str) -> float:
    return inst.metrics.wire_lane_counter.labels(lane=lane)._value.get()


def check_wire(inst, reqs, now=None):
    out = pb.GetRateLimitsResp.FromString(
        inst.get_rate_limits_wire(serialize(reqs),
                                  now_ms=now if now is not None
                                  else clock_ms()))
    return list(out.responses)


def g_req(key, hits=1, limit=100, name="wcg"):
    return RateLimitRequest(name=name, unique_key=key, hits=hits,
                            limit=limit, duration=DAY,
                            behavior=Behavior.GLOBAL)


class TestClusteredGlobalWireLane:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = cluster_mod.start(3, behaviors=BehaviorConfig(
            global_sync_wait_ms=40, global_broadcast_interval_ms=40,
            global_timeout_ms=5000),
            # promotion thresholds irrelevant here: clustered daemons
            # never use the solo hot tier
            cache_size=1 << 12)
        yield c
        c.stop()

    def _non_owner(self, cluster, key: str):
        """A daemon that does NOT own ``key`` (full name_key form)."""
        owner_d = cluster.owner_daemon_of(key)
        for i in range(3):
            if cluster.daemon_at(i) is not owner_d:
                return cluster.instance_at(i), i
        raise AssertionError("unreachable")

    def test_global_rides_columnar_lane_with_local_replica_semantics(
            self, cluster):
        """A pure-GLOBAL batch through a non-owner: wire_clustered lane,
        zero pb2 fallback, decisions = fresh local replica (oracle)."""
        inst, _ = self._non_owner(cluster, "wcg_a0")
        reqs = [g_req(f"a{i % 4}", hits=1 + i % 2) for i in range(16)]
        before = lane_count(inst, "wire_clustered")
        fallback_before = lane_count(inst, "pb2_fallback")
        now = clock_ms()
        want = Oracle().check_batch(reqs, now)
        got = check_wire(inst, reqs, now)
        assert len(got) == len(reqs)
        for i, (g, e) in enumerate(zip(got, want)):
            assert g.error == "", (i, g.error)
            assert (int(g.status), int(g.remaining), int(g.limit)) == \
                (int(e.status), int(e.remaining), int(e.limit)), i
        assert lane_count(inst, "wire_clustered") - before == len(reqs)
        assert lane_count(inst, "pb2_fallback") == fallback_before

    def test_hits_reconcile_to_owner_and_broadcast_back(self, cluster):
        """global.go semantics over the wire lane: hits served on a
        non-owner's replica converge to the owner within the sync
        window, then every replica converges via the broadcast."""
        name, key = "wcg2", "conv"
        inst, _ = self._non_owner(cluster, f"{name}_{key}")
        [r] = check_wire(inst, [g_req(key, hits=5, name=name)])
        assert r.error == "" and int(r.remaining) == 95

        def remaining_at(i):
            [rr] = check_wire(cluster.instance_at(i),
                              [g_req(key, hits=0, name=name)])
            return int(rr.remaining)

        owner_d = cluster.owner_daemon_of(f"{name}_{key}")
        owner_i = next(i for i in range(3)
                       if cluster.daemon_at(i) is owner_d)
        deadline = time.time() + 10
        while time.time() < deadline and remaining_at(owner_i) != 95:
            time.sleep(0.05)
        assert remaining_at(owner_i) == 95, \
            "owner never applied wire-queued async hits"
        deadline = time.time() + 10
        while time.time() < deadline and any(
                remaining_at(i) != 95 for i in range(3)):
            time.sleep(0.05)
        assert [remaining_at(i) for i in range(3)] == [95] * 3, \
            "replicas did not converge via broadcast"

    def test_owner_entry_queues_broadcast(self, cluster):
        """A GLOBAL batch through the OWNER daemon's wire lane must
        broadcast merged state to the replicas (queue_update_raw)."""
        name, key = "wcg3", "ownr"
        owner_d = cluster.owner_daemon_of(f"{name}_{key}")
        owner_i = next(i for i in range(3)
                       if cluster.daemon_at(i) is owner_d)
        inst = cluster.instance_at(owner_i)
        before = lane_count(inst, "wire_clustered")
        [r] = check_wire(inst, [g_req(key, hits=7, name=name)])
        assert r.error == "" and int(r.remaining) == 93
        assert lane_count(inst, "wire_clustered") - before == 1

        def remaining_at(i):
            [rr] = check_wire(cluster.instance_at(i),
                              [g_req(key, hits=0, name=name)])
            return int(rr.remaining)

        deadline = time.time() + 10
        while time.time() < deadline and any(
                remaining_at(i) != 93 for i in range(3)):
            time.sleep(0.05)
        assert [remaining_at(i) for i in range(3)] == [93] * 3, \
            "owner-side wire batch never broadcast to replicas"

    def test_mixed_batch_splits_global_local_rest_forwarded(self, cluster):
        """GLOBAL rows answer locally while sibling non-GLOBAL rows in
        the same batch still ring-forward, all in one columnar pass."""
        inst, _ = self._non_owner(cluster, "wcg4_m0")
        reqs = []
        for i in range(10):
            reqs.append(g_req(f"m{i}", name="wcg4"))
            reqs.append(RateLimitRequest(
                name="wcg4", unique_key=f"p{i}", hits=1, limit=9,
                duration=DAY, algorithm=Algorithm.TOKEN_BUCKET))
        before = lane_count(inst, "wire_clustered")
        now = clock_ms()
        want = Oracle().check_batch(reqs, now)
        got = check_wire(inst, reqs, now)
        for i, (g, e) in enumerate(zip(got, want)):
            assert g.error == "", (i, g.error)
            assert (int(g.status), int(g.remaining)) == \
                (int(e.status), int(e.remaining)), (i, reqs[i])
        assert lane_count(inst, "wire_clustered") - before == len(reqs)

    def test_global_sharing_owner_with_forward_not_double_debited(
            self, cluster):
        """A GLOBAL row whose owner also receives forwarded non-GLOBAL
        rows from the same batch must NOT ride the forward sub-batch:
        it is answered locally and reconciles async — forwarding it too
        would debit the owner twice (and overwrite the local answer)."""
        name = "wcg6"
        inst, serving_i = self._non_owner(cluster, f"{name}_seed")
        # find a GLOBAL key and a plain key with the SAME remote owner
        gkey = pkey = None
        for i in range(300):
            k = f"x{i}"
            d = cluster.owner_daemon_of(f"{name}_{k}")
            if d is cluster.daemon_at(serving_i):
                continue
            if gkey is None:
                gkey, gowner = k, d
            elif pkey is None and d is gowner:
                pkey = k
                break
        assert gkey and pkey
        reqs = [g_req(gkey, hits=6, name=name),
                RateLimitRequest(name=name, unique_key=pkey, hits=1,
                                 limit=9, duration=DAY)]
        got = check_wire(inst, reqs)
        # GLOBAL answered from the (fresh) local replica
        assert got[0].error == "" and int(got[0].remaining) == 94
        assert got[1].error == "" and int(got[1].remaining) == 8
        owner_i = next(i for i in range(3)
                       if cluster.daemon_at(i) is gowner)

        def owner_remaining():
            [rr] = check_wire(cluster.instance_at(owner_i),
                              [g_req(gkey, hits=0, name=name)])
            return int(rr.remaining)

        # after reconcile the owner must have applied the hits exactly
        # once: 94, never 88 (double debit via forward + async queue)
        deadline = time.time() + 10
        while time.time() < deadline and owner_remaining() == 100:
            time.sleep(0.05)
        assert owner_remaining() == 94, \
            f"owner saw {100 - owner_remaining()} hits, expected 6"
        # and it must STAY 94 across further flush ticks
        time.sleep(0.3)
        assert owner_remaining() == 94

    def test_wire_and_object_path_share_one_reconcile_stream(self, cluster):
        """The same key served through the wire lane AND the object path
        between flushes must reconcile the SUM of both lanes' hits to
        the owner (the raw queue merges into the object queue)."""
        name, key = "wcg5", "both"
        inst, _ = self._non_owner(cluster, f"{name}_{key}")
        [r1] = check_wire(inst, [g_req(key, hits=3, name=name)])
        resp2 = inst.get_rate_limits([g_req(key, hits=4, name=name)],
                                     now_ms=clock_ms())[0]
        assert r1.error == "" and resp2.error == ""

        owner_d = cluster.owner_daemon_of(f"{name}_{key}")
        owner_i = next(i for i in range(3)
                       if cluster.daemon_at(i) is owner_d)

        def owner_remaining():
            [rr] = check_wire(cluster.instance_at(owner_i),
                              [g_req(key, hits=0, name=name)])
            return int(rr.remaining)

        deadline = time.time() + 10
        while time.time() < deadline and owner_remaining() != 93:
            time.sleep(0.05)
        assert owner_remaining() == 93, \
            "owner saw only one lane's hits (expected 3+4 reconciled)"

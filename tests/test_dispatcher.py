"""Dispatcher (worker-pool analog) tests: coalescing, correctness under
concurrency, error propagation."""
import threading
import time

import pytest

from gubernator_tpu.dispatcher import Dispatcher
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.types import RateLimitRequest

NOW = 1_763_000_000_000


def req(key, **kw):
    d = dict(hits=1, limit=1000, duration=600_000)
    d.update(kw)
    return RateLimitRequest(name="disp", unique_key=key, **d)


@pytest.fixture()
def engine():
    return ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                        batch_per_shard=64)


def test_single_caller(engine):
    d = Dispatcher(engine)
    try:
        r = d.check_batch([req("a")], NOW)
        assert len(r) == 1 and r[0].remaining == 999
    finally:
        d.close()


def test_concurrent_callers_share_waves_and_conserve(engine):
    d = Dispatcher(engine)
    results = []
    lock = threading.Lock()

    def worker(w):
        got = []
        for i in range(10):
            got.extend(d.check_batch([req("shared"), req(f"own_{w}_{i}")],
                                     NOW + i))
        with lock:
            results.append(got)

    try:
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # every caller got a response for each request
        assert all(len(g) == 20 for g in results)
        # the shared key must have exactly 60 hits recorded
        check = d.check_batch([req("shared", hits=0)], NOW + 100)[0]
        assert check.remaining == 1000 - 60
        # waves were actually merged (fewer launches than callers×batches)
        # — smoke: the dispatcher survived; merging is probabilistic here
    finally:
        d.close()


def test_error_propagates_to_all_callers(engine):
    d = Dispatcher(engine)

    def boom(reqs, now):
        raise RuntimeError("device on fire")

    d.engine = type("E", (), {"check_batch": staticmethod(boom)})()
    try:
        with pytest.raises(RuntimeError, match="device on fire"):
            d.check_batch([req("x")], NOW)
    finally:
        d.close()


def test_close_rejects_new_and_drains(engine):
    d = Dispatcher(engine)
    d.check_batch([req("pre")], NOW)
    d.close()
    with pytest.raises(RuntimeError):
        d.check_batch([req("post")], NOW)


def test_inline_never_starts_after_close(engine):
    """ADVICE r4 (low): a caller that passes _try_inline's first
    closing check and is then preempted across a full close() must NOT
    win the inline path — close()'s drain guarantee is that no
    dispatcher-initiated engine call STARTS after it returns (the
    close-time checkpoint snapshot depends on it).  The preemption is
    simulated deterministically: the inline mutex's acquire runs
    close() to completion before actually acquiring."""
    d = Dispatcher(engine)
    real_mu = d._inline_mu

    class RacingLock:
        def acquire(self, blocking=True):
            if not d._closing.is_set():
                d.close()  # completes fully: sets closing + drains
            return real_mu.acquire(blocking)

        def release(self):
            real_mu.release()

        def __enter__(self):
            real_mu.acquire()
            return self

        def __exit__(self, *exc):
            real_mu.release()

    d._inline_mu = RacingLock()
    assert d._try_inline() is False
    # the mutex was released on the refusal path
    assert real_mu.acquire(blocking=False)
    real_mu.release()


def test_merged_cross_now_batch_matches_sequential_oracle():
    """Per-request arrival times: a single launch holding requests from
    three different wall-clock instants (interleaved, out of order in
    the block) must produce exactly what sequential per-time execution
    would — the (row, now) segment sort orders same-key requests by
    arrival time."""
    import numpy as np

    from gubernator_tpu import Oracle, RateLimitRequest
    from gubernator_tpu.core.batch import pack_columns
    from gubernator_tpu.hashing import hash_request_keys
    from gubernator_tpu.parallel import ShardedEngine, make_mesh

    NOW = 1_776_000_000_000
    eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                        batch_per_shard=64)

    def cols(now):
        kh = hash_request_keys(["dn"] * 8, [f"k{i % 4}" for i in range(8)])
        b, _ = pack_columns(kh, np.ones(8, np.int64),
                            np.full(8, 50, np.int64),
                            np.full(8, 60_000, np.int64),
                            np.zeros(8, np.int32), np.zeros(8, np.int32),
                            np.zeros(8, np.int64), now)
        return b, kh

    # concatenate three instants SHUFFLED (T+2, T, T+1): the launch must
    # still apply each key's requests in time order
    parts = [cols(NOW + 2), cols(NOW), cols(NOW + 1)]
    batch = type(parts[0][0])(*[
        np.concatenate([np.asarray(p[0][f]) for p in parts])
        for f in range(len(parts[0][0]))])
    khash = np.concatenate([p[1] for p in parts])
    st, lim, rem, rst, full = eng.check_packed(batch, khash, NOW + 2)
    assert not full.any()

    oracle = Oracle()
    want = {}
    for t in (NOW, NOW + 1, NOW + 2):
        reqs = [RateLimitRequest(name="dn", unique_key=f"k{i % 4}",
                                 hits=1, limit=50, duration=60_000)
                for i in range(8)]
        want[t] = oracle.check_batch(reqs, t)
    for j, t in enumerate((NOW + 2, NOW, NOW + 1)):  # block order
        for i in range(8):
            g = j * 8 + i
            w = want[t][i]
            assert (int(st[g]), int(rem[g]), int(rst[g])) == \
                (int(w.status), w.remaining, w.reset_time), (t, i)


import pytest


@pytest.mark.parametrize("pipeline", ["0", "1"])
def test_dispatcher_merges_packed_jobs_across_nows(pipeline, monkeypatch):
    """Queued packed jobs with different now_ms share one launch (the
    old dispatcher quantized by timestamp and could not merge them).
    Deterministic: the engine is blocked while the jobs queue up.
    Covers BOTH dispatcher paths: synchronous check_packed (CPU
    default) and the launch/sync pipeline (TPU default, forced here
    via GUBER_PIPELINE=1)."""
    import threading

    import numpy as np

    from gubernator_tpu.core.batch import pack_columns
    from gubernator_tpu.dispatcher import Dispatcher
    from gubernator_tpu.hashing import hash_request_keys
    from gubernator_tpu.parallel import ShardedEngine, make_mesh

    monkeypatch.setenv("GUBER_PIPELINE", pipeline)
    NOW = 1_777_000_000_000
    eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                        batch_per_shard=64)
    launches = []
    release = threading.Event()
    # gate whichever entry the selected path uses
    orig = eng.launch_packed if pipeline == "1" else eng.check_packed

    entered = threading.Event()

    def gated(batch, kh, now):
        entered.set()
        release.wait(timeout=30)
        launches.append(len(kh))
        return orig(batch, kh, now)

    if pipeline == "1":
        eng.launch_packed = gated
    else:
        eng.check_packed = gated
    disp = Dispatcher(eng, max_delay_ms=0.2)

    def cols(now):
        kh = hash_request_keys(["dm"] * 4, [f"q{i}" for i in range(4)])
        b, _ = pack_columns(kh, np.ones(4, np.int64),
                            np.full(4, 50, np.int64),
                            np.full(4, 60_000, np.int64),
                            np.zeros(4, np.int32), np.zeros(4, np.int32),
                            np.zeros(4, np.int64), now)
        return b, kh

    # Force the queue path for every caller (the idle-inline fast path
    # would otherwise run job 1 in its caller's thread and leave the
    # worker free to drain jobs 2/3 early): with _inline_mu held, the
    # first job blocks the WORKER inside the engine call and the other
    # two queue up behind it, merging into ONE later launch.
    disp._inline_mu.acquire()
    try:
        threads = []
        for t in range(3):
            b, kh = cols(NOW + t)

            def call(b=b, kh=kh, t=t):
                disp.check_packed(b, kh, NOW + t)

            th = threading.Thread(target=call)
            th.start()
            threads.append(th)
            if t == 0:
                assert entered.wait(timeout=30)
    finally:
        disp._inline_mu.release()
    deadline = time.monotonic() + 30
    while disp._queue.qsize() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert disp._queue.qsize() >= 2
    release.set()
    for th in threads:
        th.join(timeout=60)
    assert launches[0] == 4  # the blocked first job
    assert launches[1:] == [8]  # jobs 2 and 3 merged despite nows
    disp.close()


def test_mixed_wave_cross_now_merges_list_and_packed_jobs():
    """A wave holding object-lane jobs at different nows plus a packed
    job merges into one launch, with exact sequential-oracle results."""
    import threading

    import numpy as np

    from gubernator_tpu import Oracle, RateLimitRequest
    from gubernator_tpu.core.batch import pack_columns
    from gubernator_tpu.dispatcher import Dispatcher
    from gubernator_tpu.hashing import hash_request_keys
    from gubernator_tpu.parallel import ShardedEngine, make_mesh

    NOW = 1_779_000_000_000
    eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                        batch_per_shard=64)
    launches = []
    release = threading.Event()
    entered = threading.Event()  # the blocker reached the engine
    orig_cp = eng.check_packed
    orig_cb = eng.check_batch

    def gated_cp(batch, kh, now):
        entered.set()
        release.wait(timeout=30)
        launches.append(("packed", len(kh)))
        return orig_cp(batch, kh, now)

    def gated_cb(reqs_, now):
        entered.set()
        release.wait(timeout=30)
        launches.append(("list", len(reqs_)))
        return orig_cb(reqs_, now)

    eng.check_packed = gated_cp
    eng.check_batch = gated_cb
    disp = Dispatcher(eng, max_delay_ms=0.2)

    def reqs(tag):
        return [RateLimitRequest(name="mw", unique_key=f"k{i % 3}",
                                 hits=1, limit=50, duration=60_000)
                for i in range(6)]

    def packed_cols(now):
        kh = hash_request_keys(["mw"] * 6, [f"k{i % 3}" for i in range(6)])
        b, _ = pack_columns(kh, np.ones(6, np.int64),
                            np.full(6, 50, np.int64),
                            np.full(6, 60_000, np.int64),
                            np.zeros(6, np.int32), np.zeros(6, np.int32),
                            np.zeros(6, np.int64), now)
        return b, kh

    results = {}
    # Force the queue path for ALL callers (see the inline-fast-path
    # note in the merge test above): job 0 blocks the WORKER inside the
    # engine; the rest queue up behind it.  _inline_mu stays held until
    # every job is IN the queue — the try starts immediately so any
    # assert in the setup still releases the mutex and the blocker.
    disp._inline_mu.acquire()
    try:
        threads = [threading.Thread(
            target=lambda: results.setdefault(
                "blocker", disp.check_batch(reqs(0), NOW)))]
        threads[0].start()
        assert entered.wait(timeout=30)  # worker is held in the engine
        threads.append(threading.Thread(
            target=lambda: results.setdefault(
                "list1", disp.check_batch(reqs(1), NOW + 1))))
        threads.append(threading.Thread(
            target=lambda: results.setdefault(
                "list2", disp.check_batch(reqs(2), NOW + 2))))
        b, kh = packed_cols(NOW + 3)
        threads.append(threading.Thread(
            target=lambda: results.setdefault(
                "packed", disp.check_packed(b, kh, NOW + 3))))
        for t in threads[1:]:
            t.start()
        # deterministic: all three jobs must be IN the queue pre-release
        import time as _t

        deadline = _t.monotonic() + 30
        while disp._queue.qsize() < 3 and _t.monotonic() < deadline:
            _t.sleep(0.01)
        assert disp._queue.qsize() >= 3
    finally:
        disp._inline_mu.release()
        release.set()
    for t in threads:
        t.join(timeout=60)
    # blocker launched alone (it held the dispatcher while the rest
    # queued; engine.check_batch delegates to check_packed internally,
    # so its one launch trips both gates); the remaining three instants
    # merged into ONE launch
    assert launches[:2] == [("list", 6), ("packed", 6)]
    assert launches[2:] == [("packed", 18)], launches
    # exact parity with sequential per-time application
    oracle = Oracle()
    want = {t: oracle.check_batch(reqs(0), NOW + t) for t in range(4)}
    for tag, t in (("blocker", 0), ("list1", 1), ("list2", 2)):
        got = results[tag]
        for i, (w, g) in enumerate(zip(want[t], got)):
            assert (int(g.status), g.remaining) == \
                (int(w.status), w.remaining), (tag, i)
    st, lim, rem, rst, full = results["packed"]
    for i, w in enumerate(want[3]):
        assert (int(st[i]), int(rem[i])) == (int(w.status), w.remaining)
    disp.close()


def test_result_timeout_env_override(engine, monkeypatch):
    """GUBER_RESULT_TIMEOUT_S must override the per-instance wait cap
    (cold on-chip wave compiles are 250-305 s; the 120 s default
    silently killed the round-5 live-window service sections), and a
    malformed value must fall back to the class default."""
    monkeypatch.setenv("GUBER_RESULT_TIMEOUT_S", "900")
    d = Dispatcher(engine)
    try:
        assert d.RESULT_TIMEOUT_S == 900.0
        assert Dispatcher.RESULT_TIMEOUT_S == 120.0  # class untouched
    finally:
        d.close()
    for bad in ("not-a-number", "0", "-5", "nan", "inf", "-inf",
                "Infinity"):
        monkeypatch.setenv("GUBER_RESULT_TIMEOUT_S", bad)
        d = Dispatcher(engine)
        try:
            # malformed/zero/negative/NaN all keep the default — a 0 s
            # wait would fail every queued wave instantly
            assert d.RESULT_TIMEOUT_S == 120.0, bad
        finally:
            d.close()

"""Dispatcher (worker-pool analog) tests: coalescing, correctness under
concurrency, error propagation."""
import threading
import time

import pytest

from gubernator_tpu.dispatcher import Dispatcher
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.types import RateLimitRequest

NOW = 1_763_000_000_000


def req(key, **kw):
    d = dict(hits=1, limit=1000, duration=600_000)
    d.update(kw)
    return RateLimitRequest(name="disp", unique_key=key, **d)


@pytest.fixture()
def engine():
    return ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                        batch_per_shard=64)


def test_single_caller(engine):
    d = Dispatcher(engine)
    try:
        r = d.check_batch([req("a")], NOW)
        assert len(r) == 1 and r[0].remaining == 999
    finally:
        d.close()


def test_concurrent_callers_share_waves_and_conserve(engine):
    d = Dispatcher(engine)
    results = []
    lock = threading.Lock()

    def worker(w):
        got = []
        for i in range(10):
            got.extend(d.check_batch([req("shared"), req(f"own_{w}_{i}")],
                                     NOW + i))
        with lock:
            results.append(got)

    try:
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # every caller got a response for each request
        assert all(len(g) == 20 for g in results)
        # the shared key must have exactly 60 hits recorded
        check = d.check_batch([req("shared", hits=0)], NOW + 100)[0]
        assert check.remaining == 1000 - 60
        # waves were actually merged (fewer launches than callers×batches)
        # — smoke: the dispatcher survived; merging is probabilistic here
    finally:
        d.close()


def test_error_propagates_to_all_callers(engine):
    d = Dispatcher(engine)

    def boom(reqs, now):
        raise RuntimeError("device on fire")

    d.engine = type("E", (), {"check_batch": staticmethod(boom)})()
    try:
        with pytest.raises(RuntimeError, match="device on fire"):
            d.check_batch([req("x")], NOW)
    finally:
        d.close()


def test_close_rejects_new_and_drains(engine):
    d = Dispatcher(engine)
    d.check_batch([req("pre")], NOW)
    d.close()
    with pytest.raises(RuntimeError):
        d.check_batch([req("post")], NOW)

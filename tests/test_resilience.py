"""Failure-domain resilience (ISSUE 5): fault injection, degraded-mode
owner fallback, health-gated ring, overload shedding, drain.

Pinned here:
- chaos soak: a faultpoint kills one owner mid-load on a 3-daemon
  cluster under 16 concurrent callers — clients observe ZERO error rows
  (degraded flags instead), hit counts reconcile exactly on recovery,
  and the ejected peer's keys rehome and return with no flapping
  (ring-generation delta is exactly eject + readmit);
- fault harness: spec grammar, deterministic replay, loud unknown
  points, HTTP (`/debug/faults`) and CLI (`guber-cli debug faults`)
  arming, the injected-fault metric;
- overload admission: queue-full / deadline / drain shedding with
  `ResourceExhausted`, cheap and observable, accepted work completes;
- drain-aware `/healthz`: 503 "draining" during the close grace window,
  `drain_started`/`drain_completed` flight-recorder events;
- forward-failure attribution: error rows name the failed peer and
  `gubernator_forward_failed{peer_addr,reason}` counts them.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.dispatcher import (Dispatcher, ResourceExhausted,
                                       request_deadline)
from gubernator_tpu.faults import FAULT_POINTS, FaultInjected, FaultSet
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse

pytest.importorskip("gubernator_tpu.ops._native",
                    reason="resilience tests ride the columnar lanes")

DAY = 24 * 3_600_000
NOW0 = 1_770_000_000_000
LIMIT = 10 ** 6


def serialize(reqs):
    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        m.name = r.name
        m.unique_key = r.unique_key
        m.hits = r.hits
        m.limit = r.limit
        m.duration = r.duration
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
        m.burst = r.burst
    return msg.SerializeToString()


def one(key: str, hits: int, name="soak") -> bytes:
    return serialize([RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=LIMIT,
        duration=DAY)])


def wait_until(pred, timeout=30.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def gauge(g) -> float:
    return g._value.get()


# ---------------------------------------------------------------------------
# fault harness unit tests (no cluster)
# ---------------------------------------------------------------------------


class TestFaultSpec:
    def test_error_mode_with_probability(self):
        fs = FaultSet()
        fs.arm("peer_send:error:0.25")
        d = fs.describe()
        assert d["armed"] and len(d["points"]) == 1
        p = d["points"][0]
        assert (p["point"], p["mode"], p["prob"]) == \
            ("peer_send", "error", 0.25)

    def test_delay_mode_needs_duration(self):
        fs = FaultSet()
        with pytest.raises(ValueError):
            fs.arm("device_step:delay")
        fs.arm("device_step:delay:5ms:0.5")
        p = fs.describe()["points"][0]
        assert p["delay_ms"] == 5.0 and p["prob"] == 0.5

    def test_peer_tag_keeps_its_port(self):
        fs = FaultSet()
        fs.arm("peer_send@10.0.0.2:5001:error")
        p = fs.describe()["points"][0]
        assert p["tag"] == "10.0.0.2:5001" and p["mode"] == "error"
        # tagged point only fires for its tag
        with pytest.raises(FaultInjected):
            fs.fire("peer_send", "10.0.0.2:5001")
        fs.fire("peer_send", "10.0.0.9:5001")  # no raise

    def test_unknown_point_is_loud(self):
        fs = FaultSet()
        with pytest.raises(ValueError, match="unknown faultpoint"):
            fs.arm("peer_snd:error")
        assert not fs.armed  # nothing armed on a typo'd chaos run

    def test_bad_probability_rejected(self):
        fs = FaultSet()
        with pytest.raises(ValueError):
            fs.arm("peer_send:error:1.5")

    def test_deterministic_replay(self):
        def seq(seed):
            fs = FaultSet(seed=seed)
            fs.arm("peer_send:error:0.5")
            out = []
            for _ in range(64):
                try:
                    fs.fire("peer_send", "a")
                    out.append(0)
                except FaultInjected:
                    out.append(1)
            return out

        a, b = seq(7), seq(7)
        assert a == b and 0 < sum(a) < 64
        assert seq(8) != a

    def test_disarm_and_from_env(self):
        fs = FaultSet.from_env(
            {"GUBER_FAULT": "snapshot:error", "GUBER_FAULT_SEED": "3"})
        assert fs.armed and fs.seed == 3
        fs.arm("")
        assert not fs.armed
        fs.fire("snapshot")  # disarmed → no raise

    def test_should_gates_conditions(self):
        fs = FaultSet()
        fs.arm("peer_circuit:error")
        assert fs.should("peer_circuit", "x") is True
        fs.clear()
        assert fs.should("peer_circuit", "x") is False

    def test_catalog_documented(self):
        # RESILIENCE.md carries the operator-facing catalog; keep the
        # code-side one non-empty and stable in shape
        assert "peer_send" in FAULT_POINTS
        assert all(isinstance(v, str) and v for v in FAULT_POINTS.values())


# ---------------------------------------------------------------------------
# HTTP + CLI arming, injected-fault accounting
# ---------------------------------------------------------------------------


class TestFaultEndpoints:
    @pytest.fixture(scope="class")
    def solo(self):
        c = cluster_mod.start(1)
        yield c
        c.stop()

    def _get(self, url):
        with urllib.request.urlopen(url, timeout=10) as f:
            return json.loads(f.read())

    def _post(self, url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as f:
            return json.loads(f.read())

    def test_http_arm_inspect_clear(self, solo):
        url = solo.http_address(0) + "/debug/faults"
        out = self._post(url, {"spec": "device_step:delay:1ms",
                               "seed": 11})
        assert out["armed"] and out["seed"] == 11
        got = self._get(url)
        assert got["points"][0]["point"] == "device_step"
        assert sorted(got["catalog"]) == sorted(FAULT_POINTS)
        out = self._post(url, {"clear": True})
        assert not out["armed"]

    def test_http_bad_spec_is_400(self, solo):
        url = solo.http_address(0) + "/debug/faults"
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(url, {"spec": "nope:error"})
        assert ei.value.code == 400
        assert not solo.instance_at(0).faults.armed

    def test_cli_round_trip(self, solo, capsys):
        from gubernator_tpu.cmd.cli import main

        base = solo.http_address(0)
        assert main(["debug", "faults", "--url", base, "--set",
                     "wire_ingest:error:0.5", "--seed", "5"]) == 0
        out = capsys.readouterr().out
        assert "ARMED" in out and "wire_ingest" in out
        assert main(["debug", "faults", "--url", base, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["armed"] and doc["seed"] == 5
        assert main(["debug", "faults", "--url", base, "--clear"]) == 0
        assert "disarmed" in capsys.readouterr().out

    def test_injected_fault_raises_and_counts(self, solo):
        inst = solo.instance_at(0)
        inst.faults.arm("wire_ingest:error")
        try:
            with pytest.raises(FaultInjected):
                inst.get_rate_limits_wire(one("fi", 1), now_ms=NOW0)
            assert inst.metrics.fault_injected.labels(
                point="wire_ingest")._value.get() >= 1
            fired = inst.faults.describe()["points"][0]["fired"]
            assert fired >= 1
        finally:
            inst.faults.clear()
        # disarmed again: the same call serves
        out = pb.GetRateLimitsResp.FromString(
            inst.get_rate_limits_wire(one("fi", 1), now_ms=NOW0))
        assert out.responses[0].error == ""


# ---------------------------------------------------------------------------
# chaos soak: owner kill → degrade → eject/rehome → recover → reconcile
# ---------------------------------------------------------------------------


SOAK_B = BehaviorConfig(
    batch_timeout_ms=400, batch_wait_ms=100,
    peer_retry_limit=1, peer_retry_backoff_ms=5,
    peer_circuit_threshold=2, peer_circuit_cooldown_ms=250,
    peer_eject_after_ms=300, peer_readmit_after_ms=250,
    global_sync_wait_ms=100)


class TestChaosSoak:
    N_THREADS = 16

    def _hammer(self, c, keys, hits, reps, ledger=None, expect_flag=None):
        """16 callers over daemons 0/1; every response must be an
        error-free row (zero lost responses, zero error rows).
        ``ledger`` accumulates hits per key; ``expect_flag`` maps
        key → required value of the degraded metadata flag."""
        errs = []
        mu = threading.Lock()

        def worker(t):
            inst = c.instance_at(t % 2)
            try:
                for r in range(reps):
                    key = keys[(t + r) % len(keys)]
                    out = pb.GetRateLimitsResp.FromString(
                        inst.get_rate_limits_wire(
                            one(key, hits),
                            now_ms=NOW0 + 1 + r))
                    assert len(out.responses) == 1, "lost response"
                    resp = out.responses[0]
                    assert resp.error == "", f"{key}: {resp.error}"
                    if expect_flag is not None:
                        want = expect_flag[key]
                        got = resp.metadata.get("degraded", "") == "true"
                        assert got == want, \
                            f"{key}: degraded={got}, want {want}"
                    if ledger is not None:
                        with mu:
                            ledger[key] = ledger.get(key, 0) + hits
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        ths = [threading.Thread(target=worker, args=(t,))
               for t in range(self.N_THREADS)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=120)
        assert not any(th.is_alive() for th in ths), "stuck caller"
        assert not errs, errs[:3]

    def test_owner_kill_degrade_reconcile_recover(self):
        c = cluster_mod.start(3, behaviors=SOAK_B)
        try:
            self._run_soak(c)
        finally:
            c.stop()

    def _run_soak(self, c):
        i0, i1 = c.instance_at(0), c.instance_at(1)
        victim = c.daemon_at(2)
        vaddr = c.peer_at(2).grpc_address

        # split a key universe by membership owner
        vkeys, okeys, wkeys = [], [], []
        for i in range(400):
            k = f"k{i}"
            owned = c.owner_daemon_of("soak_" + k) is victim
            if owned and len(vkeys) < 6:
                vkeys.append(k)
            elif owned and len(wkeys) < 4:
                wkeys.append(k)  # uncounted warm-kill keys
            elif not owned and len(okeys) < 4:
                okeys.append(k)
            if len(vkeys) == 6 and len(okeys) == 4 and len(wkeys) == 4:
                break
        assert len(vkeys) == 6 and len(okeys) == 4 and len(wkeys) == 4

        ledger: dict = {}
        keys = vkeys + okeys
        # warm every counted key's row at its owner (hits=0 through
        # both caller daemons), as the PR-3 conservation test does:
        # concurrent COLD-create across lanes can lose a call's hits
        # (pre-existing dispatcher bug, ROADMAP open item — repro in
        # its entry), and this soak pins the resilience layer, not
        # that bug
        for inst in (i0, i1):
            for k in keys + wkeys:
                inst.get_rate_limits_wire(one(k, 0), now_ms=NOW0)
        gen0 = [gauge(i.metrics.ring_generation) for i in (i0, i1)]

        # phase A — healthy: nothing degraded, normal forwards
        self._hammer(c, keys, hits=2, reps=6, ledger=ledger,
                     expect_flag={k: False for k in keys})

        # kill: every send to the victim fails, deterministically
        for inst in (i0, i1):
            inst.faults.arm(f"peer_send@{vaddr}:error", seed=7)

        # phase B1 — drive failures (uncounted keys) until BOTH
        # daemons' health gates eject the victim; responses stay
        # error-free the whole way (degraded fallback from the first
        # failed forward, before any ejection)
        def both_ejected():
            self._hammer(c, wkeys, hits=1, reps=2)
            return all(gauge(i.metrics.ring_ejected_peers) == 1
                       for i in (i0, i1))

        wait_until(both_ejected, timeout=60, what="both daemons ejecting "
                   "the victim from their routing rings")

        # phase B2 — steady degraded state, counted: victim-owned keys
        # answer with the degraded flag (rehomed locally or flagged by
        # the rehome target), healthy keys stay clean
        flags = {k: True for k in vkeys}
        flags.update({k: False for k in okeys})
        self._hammer(c, keys, hits=3, reps=6, ledger=ledger,
                     expect_flag=flags)
        assert gauge(i0.metrics.peer_circuit_open_counter.labels(
            peer_addr=vaddr)) >= 1
        deg_total = sum(
            gauge(i.metrics.degraded_served.labels(peer_addr=vaddr))
            for i in (i0, i1))
        assert deg_total > 0

        # phase C — recover: clear the faults; the ring probe closes
        # the victim's circuit, hysteresis readmits it
        for inst in (i0, i1):
            inst.faults.clear()

        def both_readmitted():
            # light uncounted traffic keeps the routing gate re-deriving
            self._hammer(c, okeys[:1], hits=0, reps=1)
            return all(gauge(i.metrics.ring_ejected_peers) == 0
                       for i in (i0, i1))

        wait_until(both_readmitted, timeout=60,
                   what="victim readmitted on both daemons")

        # reconcile: queued degraded hits flush to the recovered owner.
        # "queues empty" is not enough — a tick POPS the queues before
        # its flush lands (and requeues on failure), so wait for the
        # conservation numbers themselves to converge.
        def conserved():
            for inst in (i0, i1):
                gm = inst.global_manager
                if gm is not None:
                    gm._hits_loop.poke()
            for key in keys:
                out = pb.GetRateLimitsResp.FromString(
                    i0.get_rate_limits_wire(one(key, 0),
                                            now_ms=NOW0 + 9_000))
                if LIMIT - int(out.responses[0].remaining) \
                        != ledger[key]:
                    return False
            return True

        wait_until(conserved, timeout=60, interval=0.2,
                   what="degraded hits reconciling exactly to the "
                        "recovered owner")

        # no flapping: one outage costs exactly two ring bumps
        for i, inst in enumerate((i0, i1)):
            delta = gauge(inst.metrics.ring_generation) - gen0[i]
            assert delta == 2, f"daemon {i}: ring flapped ({delta} bumps)"

        # exact conservation: every counted hit debited exactly once,
        # observable identically through both healthy daemons
        for key in keys:
            seen = set()
            for inst in (i0, i1):
                out = pb.GetRateLimitsResp.FromString(
                    inst.get_rate_limits_wire(one(key, 0),
                                              now_ms=NOW0 + 10_000))
                resp = out.responses[0]
                assert resp.error == ""
                assert "degraded" not in resp.metadata
                seen.add(int(resp.remaining))
            assert len(seen) == 1, f"{key}: split view {seen}"
            debited = LIMIT - seen.pop()
            assert debited == ledger[key], \
                f"{key}: {debited} debited != {ledger[key]} sent"


# ---------------------------------------------------------------------------
# overload admission control
# ---------------------------------------------------------------------------


class _GatedEngine:
    """check_batch blocks until released — deterministic backlog."""

    def __init__(self):
        self.gate = threading.Event()

    def check_batch(self, reqs, now_ms):
        assert self.gate.wait(30), "test gate never released"
        return [RateLimitResponse(limit=r.limit, remaining=r.limit - r.hits)
                for r in reqs]


def _req(key, hits=1):
    return RateLimitRequest(name="ovl", unique_key=key, hits=hits,
                            limit=1000, duration=DAY)


class TestOverloadShedding:
    def test_queue_full_sheds_resource_exhausted(self):
        from gubernator_tpu.metrics import Metrics

        m = Metrics()
        eng = _GatedEngine()
        d = Dispatcher(eng, max_wave=4, max_delay_ms=0, metrics=m)
        d.admission_limit = 8
        done, errs = [], []

        def caller(i):
            try:
                done.append(d.check_batch([_req(f"q{i}_{j}")
                                           for j in range(4)], NOW0))
            except ResourceExhausted:
                errs.append(i)

        try:
            ths = []
            # one wave (4 rows) blocks in the engine; the queue then
            # holds at most admission_limit rows; the rest shed
            for i in range(6):
                th = threading.Thread(target=caller, args=(i,))
                th.start()
                ths.append(th)
                time.sleep(0.05)
            wait_until(lambda: len(errs) >= 1, timeout=10,
                       what="a shed caller")
            eng.gate.set()
            for th in ths:
                th.join(timeout=30)
            assert len(done) + len(errs) == 6
            assert done, "every caller shed — gate broken"
            # accepted callers all completed with full responses
            assert all(len(r) == 4 for r in done)
            assert m.admission_shed.labels(
                reason="queue_full")._value.get() >= 4
        finally:
            eng.gate.set()
            d.close()

    def test_deadline_shed_only_with_backlog(self):
        from gubernator_tpu.metrics import Metrics

        m = Metrics()
        eng = _GatedEngine()
        eng.gate.set()
        d = Dispatcher(eng, max_wave=4, metrics=m)
        try:
            # empty queue: any deadline admits (work launches at once)
            d.admit(4, deadline_s=0.001)
            # backlog + observed slow waves: projected wait exceeds the
            # caller deadline → shed
            with d._tel_mu:
                d._recent_sizes.append(4)
                d._recent_durs.append(5.0)
            with d._submit_mu:
                d._queued_rows = 8
            with pytest.raises(ResourceExhausted):
                d.admit(4, deadline_s=1.0)
            assert m.admission_shed.labels(
                reason="deadline")._value.get() == 4
            # a generous deadline still admits through the same backlog
            d.admit(4, deadline_s=60.0)
            # the ContextVar front door carries the deadline too
            with request_deadline(1.0):
                with pytest.raises(ResourceExhausted):
                    d.admit(4)
            with d._submit_mu:
                d._queued_rows = 0
        finally:
            d.close()

    def test_drain_sheds_new_ingress(self):
        from gubernator_tpu.metrics import Metrics

        m = Metrics()
        eng = _GatedEngine()
        eng.gate.set()
        d = Dispatcher(eng, metrics=m)
        try:
            assert len(d.check_batch([_req("d0")], NOW0)) == 1
            d.drain()
            # new ingress (the admit gate every client path runs) sheds
            with pytest.raises(ResourceExhausted):
                d.admit(1)
            assert m.admission_shed.labels(
                reason="draining")._value.get() == 1
            # but in-flight / peer-side work still completes: drain
            # finishes what's already inside the daemon
            assert len(d.check_batch([_req("d1")], NOW0)) == 1
        finally:
            d.close()

    def test_admission_stats_in_debug(self):
        eng = _GatedEngine()
        eng.gate.set()
        d = Dispatcher(eng)
        try:
            d.check_batch([_req("s0")], NOW0)
            st = d.debug_stats()["admission"]
            assert st["limit_rows"] == d.admission_limit
            assert st["queued_rows"] == 0 and not st["draining"]
        finally:
            d.close()


# ---------------------------------------------------------------------------
# drain-aware /healthz
# ---------------------------------------------------------------------------


class TestDrain:
    def test_healthz_reports_draining_during_grace(self):
        c = cluster_mod.start(1, drain_grace_ms=800)
        d = c.daemon_at(0)
        url = c.http_address(0) + "/healthz"
        try:
            with urllib.request.urlopen(url, timeout=10) as f:
                assert json.loads(f.read())["status"] == "healthy"
            closer = threading.Thread(target=d.close)
            closer.start()

            def draining():
                try:
                    with urllib.request.urlopen(url, timeout=2) as f:
                        json.loads(f.read())
                    return False
                except urllib.error.HTTPError as e:
                    body = json.loads(e.read())
                    return (e.code == 503
                            and body["status"] == "draining")
                except OSError:
                    return False

            wait_until(draining, timeout=5,
                       what="healthz flipping to 503 draining")
            assert gauge(d.instance.metrics.draining) == 1
            closer.join(timeout=30)
            assert not closer.is_alive()
            kinds = [e["kind"] for e in d.instance.recorder.events()]
            assert "drain_started" in kinds
            assert "drain_completed" in kinds
            assert kinds.index("drain_started") < \
                kinds.index("drain_completed")
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# chaos-matrix harness smoke (tools/chaos_matrix.py, `make chaos`)
# ---------------------------------------------------------------------------


class TestChaosMatrixSmoke:
    def test_matrix_subset_runs_clean(self):
        from tools.chaos_matrix import MATRIX, run_matrix

        from gubernator_tpu.faults import FAULT_POINTS

        # the full matrix is `make chaos`; tier-1 smokes a cross-layer
        # subset and the driver-coverage lint
        assert set(MATRIX) == set(FAULT_POINTS)
        verdict = run_matrix(
            points=["wire_ingest", "peer_send", "device_step",
                    "snapshot"])
        assert verdict["ok"], verdict["failed"]
        assert verdict["exercised"] >= 7


# ---------------------------------------------------------------------------
# forward-failure attribution (ISSUE 5 small fix)
# ---------------------------------------------------------------------------


class TestForwardFailedAttribution:
    def test_error_rows_name_the_peer_and_count(self):
        b = BehaviorConfig(batch_timeout_ms=200, batch_wait_ms=100,
                           peer_retry_limit=1, peer_retry_backoff_ms=5,
                           peer_circuit_threshold=2,
                           peer_circuit_cooldown_ms=700,
                           peer_degraded_fallback=False,
                           peer_health_gate=False)
        c = cluster_mod.start(2, behaviors=b)
        try:
            inst = c.instance_at(0)
            addr1 = c.peer_at(1).grpc_address
            keys = []
            for i in range(200):
                k = f"ff{i}"
                if c.owner_daemon_of("soak_" + k) is c.daemon_at(1):
                    keys.append(k)
                if len(keys) == 3:
                    break
            assert keys
            c.daemon_at(1).close()
            out = pb.GetRateLimitsResp.FromString(
                inst.get_rate_limits_wire(
                    serialize([RateLimitRequest(
                        name="soak", unique_key=k, hits=1, limit=10,
                        duration=DAY) for k in keys]),
                    now_ms=NOW0))
            for r in out.responses:
                assert "while fetching rate limit from peer" in r.error
                assert addr1 in r.error  # WHICH owner failed
            fam = inst.metrics.forward_failed.collect()[0]
            failed = sum(s.value for s in fam.samples
                         if s.name.endswith("_total")
                         and s.labels.get("peer_addr") == addr1)
            assert failed >= len(keys)
        finally:
            c.stop()

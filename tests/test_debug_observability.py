"""End-to-end observability: /debug/events + deep /healthz + the CLI
round trips, and the acceptance scenario — a simulated slow wave is
DIAGNOSED (stalled gauge on /metrics, wave_stalled event with non-empty
error/trace on /debug/events, telemetry block in the bench snapshot)
before the caller's timeout fires."""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.netutil import free_port
from gubernator_tpu.oracle import OracleEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRACE_ID = "ab" * 16
TRACEPARENT = f"00-{TRACE_ID}-{'cd' * 8}-01"


@pytest.fixture(scope="module")
def daemon():
    # a tiny stall threshold so the watchdog (poll interval threshold/4)
    # flags a slow wave within the test's injected 1.2 s engine delay
    os.environ["GUBER_STALL_THRESHOLD_S"] = "0.25"
    try:
        # OracleEngine: the observability layer under test is engine-
        # agnostic; the pure-Python engine keeps this e2e suite runnable
        # without the jax sharded stack
        d = spawn_daemon(DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{free_port()}",
            http_listen_address=f"127.0.0.1:{free_port()}",
            cache_size=1 << 10), engine=OracleEngine())
    finally:
        del os.environ["GUBER_STALL_THRESHOLD_S"]
    yield d
    d.close()


def _get(daemon, path, timeout=10):
    url = f"http://127.0.0.1:{daemon.http_port}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as f:
        return f.read()


def _post_check(daemon, key, timeout=60):
    body = json.dumps({"requests": [{
        "name": "obs", "unique_key": key, "hits": 1, "limit": 100,
        "duration": 60_000}]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{daemon.http_port}/v1/GetRateLimits",
        data=body, headers={"Content-Type": "application/json",
                            "traceparent": TRACEPARENT})
    with urllib.request.urlopen(req, timeout=timeout) as f:
        return json.loads(f.read())


def test_debug_events_round_trip_with_trace(daemon):
    out = _post_check(daemon, "k_events")
    assert out["responses"][0]["error"] == ""
    body = json.loads(_get(daemon, "/debug/events"))
    evs = body["events"]
    kinds = {e["kind"] for e in evs}
    assert "wave_launched" in kinds and "wave_completed" in kinds
    # the HTTP handler's traceparent rode into the wave events
    assert any(e.get("trace") == TRACE_ID for e in evs)
    # ordering + limit
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs)
    limited = json.loads(_get(daemon, "/debug/events?limit=2"))["events"]
    assert len(limited) == 2 and limited[-1]["seq"] == seqs[-1]


def test_healthz_deep_reports_dispatcher_state(daemon):
    shallow = json.loads(_get(daemon, "/healthz"))
    assert shallow["status"] == "healthy"
    assert "dispatcher" not in shallow
    deep = json.loads(_get(daemon, "/healthz?deep=1"))
    disp = deep["dispatcher"]
    for k in ("queue_depth", "in_flight", "last_wave_age_s", "stalled",
              "waves", "stall_events", "timeouts", "stall_threshold_s",
              "result_timeout_s"):
        assert k in disp, k
    assert disp["waves"] >= 1  # the daemon warmup wave at minimum
    assert disp["stall_threshold_s"] == pytest.approx(0.25)


def test_slow_wave_is_diagnosed_before_caller_timeout(daemon):
    """The acceptance scenario: engine delay (1.2 s) > watchdog
    threshold (0.25 s) but far below RESULT_TIMEOUT_S (120 s) — the
    stall must be visible on /metrics and /debug/events WHILE the wave
    is still in flight, and the caller must then succeed normally."""
    import bench

    inst = daemon.instance
    eng = inst.engine
    orig = eng.check_batch

    def slow(reqs, now):
        time.sleep(1.2)
        return orig(reqs, now)

    eng.check_batch = slow
    result = {}
    try:
        t = threading.Thread(target=lambda: result.update(
            _post_check(daemon, "k_slow")))
        t.start()
        # the gauge must flip while the wave is in flight
        deadline = time.monotonic() + 10
        flipped = False
        while time.monotonic() < deadline:
            text = _get(daemon, "/metrics").decode()
            if "gubernator_dispatcher_stalled 1.0" in text:
                flipped = True
                break
            time.sleep(0.05)
        assert flipped, "stalled gauge never flipped on /metrics"
        assert t.is_alive(), "diagnosis must precede the wave finishing"
        evs = json.loads(_get(daemon, "/debug/events"))["events"]
        stalls = [e for e in evs if e["kind"] == "wave_stalled"]
        assert stalls, "no wave_stalled event on /debug/events"
        assert stalls[-1]["error"], "stall event error field is empty"
        assert stalls[-1]["trace"] == TRACE_ID, \
            "stall event must carry the caller's trace id"
        t.join(timeout=60)
        # the caller did NOT time out: the stall was a diagnosis only
        assert result["responses"][0]["error"] == ""
    finally:
        eng.check_batch = orig
    # recovery: gauge clears once the wave completes
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if ("gubernator_dispatcher_stalled 0.0"
                in _get(daemon, "/metrics").decode()):
            break
        time.sleep(0.05)
    else:
        pytest.fail("stalled gauge never cleared after recovery")
    # ...and the bench telemetry block sees the same stall
    snap = bench._telemetry_rows(inst)
    assert snap["stall_events"] >= 1
    assert snap["timeouts"] == 0
    assert snap["wave_duration_p99_ms"] is not None
    assert snap["wave_size_p50"] >= 1


def test_cli_debug_events_subcommand(daemon):
    _post_check(daemon, "k_cli")
    r = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "events", "--url", f"http://127.0.0.1:{daemon.http_port}",
         "--json"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    evs = json.loads(r.stdout)["events"]
    assert any(e["kind"] == "wave_completed" for e in evs)
    # human format + kind filter
    r2 = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "events", "--url", f"http://127.0.0.1:{daemon.http_port}",
         "--kind", "wave_completed", "--limit", "5"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 0, r2.stderr
    lines = r2.stdout.strip().splitlines()
    assert lines and all("wave_completed" in ln for ln in lines)


def test_debug_events_server_side_filters(daemon):
    """ISSUE 4 satellite: ?kind= and ?since_seq= filter on the daemon,
    so a polling CLI stops re-downloading the whole ring."""
    _post_check(daemon, "k_filter")
    evs = json.loads(_get(daemon, "/debug/events"))["events"]
    assert len(evs) >= 3
    mid = evs[len(evs) // 2]["seq"]
    filt = json.loads(_get(
        daemon, "/debug/events?kind=wave_completed"))["events"]
    assert filt and all(e["kind"] == "wave_completed" for e in filt)
    inc = json.loads(_get(
        daemon, f"/debug/events?since_seq={mid}"))["events"]
    assert inc and all(e["seq"] > mid for e in inc)
    both = json.loads(_get(
        daemon,
        f"/debug/events?kind=wave_completed&since_seq={mid}&limit=1")
    )["events"]
    assert len(both) <= 1
    for e in both:
        assert e["kind"] == "wave_completed" and e["seq"] > mid
    assert json.loads(_get(
        daemon, "/debug/events?kind=no_such_kind"))["events"] == []


def test_cli_debug_events_since_seq_flag(daemon):
    _post_check(daemon, "k_seq")
    r = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "events", "--url", f"http://127.0.0.1:{daemon.http_port}",
         "--since-seq", "999999", "--json"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["events"] == []


def test_cli_debug_topkeys_subcommand(daemon):
    """ISSUE 4: `guber-cli debug topkeys` round trip — the served key
    shows up by NAME with its hit count."""
    for _ in range(3):
        _post_check(daemon, "k_top")
    r = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "topkeys", "--url", f"http://127.0.0.1:{daemon.http_port}",
         "--json"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    body = json.loads(r.stdout)
    by_name = {e["key"]: e for e in body["keys"]}
    assert by_name["obs_k_top"]["hits"] >= 3
    # human format: one line per key, heaviest first
    r2 = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "topkeys", "--url", f"http://127.0.0.1:{daemon.http_port}",
         "--limit", "2"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 0, r2.stderr
    assert "obs_k_top" in r2.stdout
    assert "admission_err" in r2.stdout.splitlines()[0]


def test_healthz_deep_reports_analytics_block(daemon):
    deep = json.loads(_get(daemon, "/healthz?deep=1"))
    ana = deep["dispatcher"]["analytics"]
    assert ana["waves_tapped"] >= 1
    assert ana["taps_dropped"] == 0
    assert ana["k"] == 256


def test_healthcheck_cli_deep(daemon):
    r = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.healthcheck",
         "--url", f"http://127.0.0.1:{daemon.http_port}/healthz",
         "--deep"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "healthy" in r.stdout
    assert "dispatcher:" in r.stdout
    disp = json.loads(r.stdout.split("dispatcher:", 1)[1]
                      .strip().splitlines()[0])
    assert "queue_depth" in disp and "stalled" in disp

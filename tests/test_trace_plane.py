"""End-to-end distributed tracing (ISSUE 12): SpanRecorder semantics
(deterministic head sampling, bounded ring/pending, forced-sample
outcomes, tombstone routing for late adds), wave spans whose phase
children exactly partition the wave duration, cross-daemon stitching
over the raw TLV lanes on a 3-daemon cluster, ``/debug/traces`` +
``?trace=`` event filtering, slo_breach exemplars, and a 16-thread
soak asserting the recorder never builds backpressure."""
import json
import random
import threading
import time
import urllib.request

import grpc
import pytest

from gubernator_tpu import tracing
from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.oracle import OracleEngine
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.slo import SLO, SLOEngine
from gubernator_tpu.telemetry import FlightRecorder
from gubernator_tpu.tracing import (SpanRecorder, assemble, force_sample,
                                    hop_traceparent, render_waterfall,
                                    request_context, span)
from gubernator_tpu.types import RateLimitRequest

NOW = 1_791_000_000_000
TID = "ab" * 16


def req(key, name="traceco/api", hits=1, **kw):
    d = dict(limit=100_000, duration=600_000)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, hits=hits, **d)


def _tids(seed, n):
    rng = random.Random(seed)
    return [f"{rng.getrandbits(128):032x}" for _ in range(n)]


def _span(tid, sid, parent=None, name="s", start=0.0, end=1.0):
    return {"trace_id": tid, "span_id": sid, "parent_id": parent,
            "name": name, "start": start, "end": end, "attrs": {}}


# ---- SpanRecorder unit semantics ---------------------------------------


class TestHeadSampling:
    def test_seeded_decisions_are_deterministic(self):
        """Same trace id → same verdict on every recorder (the cluster
        property: assembly never sees half a trace)."""
        tids = _tids(7, 2000)
        a = SpanRecorder(sample=0.1)
        b = SpanRecorder(capacity=4, sample=0.1)
        da = [a.head_sampled(t) for t in tids]
        assert da == [b.head_sampled(t) for t in tids]
        assert da == [a.head_sampled(t) for t in tids]  # stable, not RNG
        frac = sum(da) / len(da)
        assert 0.05 < frac < 0.2, frac  # the rate is honored, roughly

    def test_rate_edges(self):
        r = SpanRecorder(sample=0.0)
        assert not r.head_sampled(TID)
        r.sample = 1.0
        assert r.head_sampled(TID)
        r.sample = 0.5
        assert not r.head_sampled("zz")  # malformed id → drop, not raise


def test_ring_bound_eviction():
    r = SpanRecorder(capacity=8, sample=1.0)
    for i in range(20):
        tid = f"{i:032x}"
        r.add(_span(tid, f"{i:016x}"))
        assert r.commit(tid)
    assert len(r) == 8
    kept = [s["trace_id"] for s in r.spans()]
    assert kept == [f"{i:032x}" for i in range(12, 20)]  # newest survive
    st = r.stats()
    assert st["spans"] == 8 and st["capacity"] == 8 and st["pending"] == 0


def test_pending_bounds_never_grow_unbounded():
    r = SpanRecorder(capacity=512, sample=1.0)
    for i in range(3 * SpanRecorder.PENDING_SPANS):
        r.add(_span(TID, f"{i:016x}"))
    assert r.stats()["pending"] == 1
    assert r.commit(TID)
    assert len(r) == SpanRecorder.PENDING_SPANS  # per-trace span cap
    assert r.stats()["dropped"] >= 2 * SpanRecorder.PENDING_SPANS
    for i in range(2 * SpanRecorder.PENDING_TRACES):
        r.add(_span(f"{i:032x}", "aa" * 8))
    assert r.stats()["pending"] <= SpanRecorder.PENDING_TRACES


def test_forced_sample_outcomes_survive_at_sample_zero():
    r = SpanRecorder(sample=0.0)
    for reason in ("shed", "degraded"):
        with request_context(None, recorder=r):
            with span(f"forced.{reason}"):
                force_sample(reason)
    with pytest.raises(RuntimeError):
        with request_context(None, recorder=r):
            with span("forced.error"):
                raise RuntimeError("boom")
    names = {s["name"] for s in r.spans()}
    assert names == {"forced.shed", "forced.degraded", "forced.error"}
    # control: the same flow without forcing drops at sample=0
    with request_context(None, recorder=r):
        with span("unforced"):
            pass
    assert "unforced" not in {s["name"] for s in r.spans()}


def test_late_adds_route_via_tombstones():
    """A pipelined wave worker can add() after the request committed;
    the remembered decision routes the span (ring vs drop)."""
    r = SpanRecorder(sample=1.0)
    assert r.commit(TID)
    r.add(_span(TID, "aa" * 8))
    assert [s["span_id"] for s in r.spans(trace_id=TID)] == ["aa" * 8]
    r.sample = 0.0
    tid2 = "cd" * 16
    assert not r.commit(tid2)
    before = r.stats()["dropped"]
    r.add(_span(tid2, "bb" * 8))
    assert r.spans(trace_id=tid2) == []
    assert r.stats()["dropped"] == before + 1


def test_exemplar_tracks_last_sampled_trace():
    r = SpanRecorder(sample=1.0)
    assert r.exemplar() is None
    r.commit(TID)
    assert r.exemplar() == {"trace_id": TID}
    r.sample = 0.0
    r.commit("cd" * 16)  # unsampled: must not steal the exemplar
    assert r.exemplar() == {"trace_id": TID}


def test_hop_span_id_is_the_minted_traceparent_parent():
    """The caller-side ``peer.forward`` hop span's id IS the span id
    sent in the outbound traceparent — the owner's request span parents
    under it, which is the whole cross-daemon stitch."""
    r = SpanRecorder(sample=1.0)
    with request_context(None, recorder=r):
        with span("grpc.GetRateLimits"):
            tp = hop_traceparent("peer.forward", attrs={"items": 3})
    hop = [s for s in r.spans() if s["name"] == "peer.forward"]
    assert len(hop) == 1
    assert hop[0]["span_id"] == tp.split("-")[2]
    assert hop[0]["attrs"]["items"] == 3
    root = [s for s in r.spans() if s["name"] == "grpc.GetRateLimits"]
    assert hop[0]["parent_id"] == root[0]["span_id"]


def test_assemble_nests_dedups_and_orphans_to_roots():
    spans = [
        _span(TID, "r" * 16, name="root", start=0.0, end=3.0),
        _span(TID, "c" * 16, parent="r" * 16, name="child",
              start=1.0, end=2.0),
        _span(TID, "c" * 16, parent="r" * 16, name="child",
              start=1.0, end=2.0),  # duplicate slice fetch: dedup
        _span(TID, "o" * 16, parent="f" * 16, name="orphan",
              start=0.5, end=0.6),  # parent unknown: surfaces as root
        _span("99" * 16, "d" * 16, name="other"),
    ]
    traces = assemble(spans, trace_id=TID)
    assert len(traces) == 1 and traces[0]["spans"] == 3
    roots = {r["name"] for r in traces[0]["roots"]}
    assert roots == {"root", "orphan"}
    root = next(r for r in traces[0]["roots"] if r["name"] == "root")
    assert [c["name"] for c in root["children"]] == ["child"]
    text = render_waterfall(traces[0])
    for name in ("root", "child", "orphan"):
        assert name in text
    assert assemble(spans)[0]["trace_id"] in (TID, "99" * 16)


def test_slo_breach_event_carries_exemplar_trace():
    rec = FlightRecorder()
    eng = SLOEngine(recorder=rec, fast_s=10.0, slow_s=20.0,
                    clock=lambda: 0.0, exemplar=lambda: TID)
    state = {"bad": 0.0, "total": 0.0}

    def source():
        state["bad"] += 10.0
        state["total"] += 10.0  # 100% bad: burns past any threshold
        return state["bad"], state["total"]

    eng.register(SLO("error_ratio", "ratio", 0.99, source))
    for t in range(8):
        eng.tick(now=float(t))
    evs = rec.events(kind="slo_breach")
    assert evs and evs[-1]["exemplar_trace"] == TID
    # a failing exemplar callable must not kill the tick
    eng2 = SLOEngine(recorder=FlightRecorder(),
                     exemplar=lambda: 1 / 0)
    eng2.register(SLO("error_ratio", "ratio", 0.99, source))
    for t in range(8):
        eng2.tick(now=float(t))


# ---- instance-level: wave spans + partition exactness ------------------


def _wave_tree(recorder, tid, deadline_s=10.0):
    """Poll until the trace assembles with a wave that has phase
    children (the dispatcher thread lands them asynchronously)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        traces = assemble(recorder.spans(), trace_id=tid)
        if traces:
            flat = []

            def walk(n):
                flat.append(n)
                for c in n.get("children", []):
                    walk(c)

            for r in traces[0]["roots"]:
                walk(r)
            waves = [n for n in flat
                     if n["name"] == "wave" and n.get("children")]
            if waves and len(traces[0]["roots"]) == 1:
                return traces[0], flat, waves
        time.sleep(0.05)
    raise AssertionError("wave span with children never assembled")


def _assert_exact_partition(wave):
    """The in-wave children tile [start, end] with no gaps or overlap
    — the PhaseLedger partition, kept as tree structure."""
    kids = wave["children"]
    assert kids, "wave has no phase children"
    assert all(k["name"].startswith("wave.") for k in kids)
    assert kids[0]["start"] == wave["start"]
    for a, b in zip(kids, kids[1:]):
        assert b["start"] == a["end"]  # contiguous by construction
    assert kids[-1]["end"] == wave["end"]  # bitwise: same cumulative walk
    total = sum(k["end"] - k["start"] for k in kids)
    assert total == pytest.approx(wave["end"] - wave["start"],
                                  rel=1e-9, abs=1e-9)


def test_wave_phase_children_exactly_partition_the_wave():
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        inst.span_recorder.sample = 1.0
        with request_context(None, recorder=inst.span_recorder):
            tid = tracing.current_trace_id()
            with span("grpc.GetRateLimits"):
                inst.get_rate_limits([req(f"pk{i}") for i in range(8)],
                                     now_ms=NOW)
        trace, flat, waves = _wave_tree(inst.span_recorder, tid)
        root = trace["roots"][0]
        assert root["name"] == "grpc.GetRateLimits"
        for wave in waves:
            _assert_exact_partition(wave)
        # the wave hangs under the request span (submit-time parent)
        names = {n["name"] for n in flat}
        assert "wave" in names
        wave_parents = {n["parent_id"] for n in waves}
        assert root["span_id"] in wave_parents
        # wave events carry the span id (join key event ↔ trace)
        evs = [e for e in inst.recorder.events(kind="wave_completed")
               if e.get("trace") == tid]
        assert evs and evs[-1].get("span_id") in {
            n["span_id"] for n in waves}
    finally:
        inst.close()


def test_shed_outcome_forces_sampling():
    from gubernator_tpu.dispatcher import ResourceExhausted

    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        inst.span_recorder.sample = 0.0
        inst.get_rate_limits([req("warm")], now_ms=NOW)
        inst.dispatcher.drain()
        with request_context(None, recorder=inst.span_recorder):
            tid = tracing.current_trace_id()
            with pytest.raises(ResourceExhausted):
                with span("grpc.GetRateLimits"):
                    inst.get_rate_limits([req("shed_k")], now_ms=NOW)
        # at sample=0 the trace survived only because the shed forced it
        spans = inst.span_recorder.spans(trace_id=tid)
        assert {s["name"] for s in spans} >= {"grpc.GetRateLimits"}
        evs = [e for e in inst.recorder.events(kind="admission_shed")
               if e.get("trace") == tid]
        assert evs and evs[-1].get("span_id")
    finally:
        inst.close()


# ---- 3-daemon cluster: cross-lane stitching ----------------------------


def test_three_daemon_cross_lane_stitch():
    """The acceptance shape: client → daemon 0 (traceparent metadata)
    → raw-TLV forward lanes → owner daemons.  Stitching the three
    ``/debug/traces`` slices yields ONE tree: the owner-side request
    span parents under daemon 0's ``peer.forward`` hop, its wave hangs
    below, and the wave's phase children exactly partition it."""
    from gubernator_tpu import cluster as cluster_mod

    c = cluster_mod.start(3)
    try:
        for i in range(3):
            c.instance_at(i).span_recorder.sample = 1.0
        msg = pb.GetRateLimitsReq()
        for i in range(40):
            q = msg.requests.add()
            q.name, q.unique_key = "stitch", f"sk{i}"
            q.hits, q.limit, q.duration = 1, 100_000, 600_000
        ch = grpc.insecure_channel(c.grpc_address(0))
        call = ch.unary_unary(
            "/pb.gubernator.V1/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString)
        resp = call(msg, timeout=60,
                    metadata=[("traceparent",
                               f"00-{TID}-00f067aa0ba902b7-01")])
        assert len(resp.responses) == 40

        deadline = time.monotonic() + 15.0
        stitched = None
        while time.monotonic() < deadline and stitched is None:
            spans = []
            for i in range(3):
                spans.extend(c.instance_at(i).span_recorder.spans(
                    trace_id=TID))
            traces = assemble(spans, trace_id=TID)
            if len(traces) == 1 and len(traces[0]["roots"]) == 1:
                root = traces[0]["roots"][0]
                hops = {n["span_id"]: n for n in root["children"]
                        if n["name"] == "peer.forward"}
                owner_reqs = [
                    n for h in hops.values() for n in h["children"]
                    if n["name"] == "grpc.GetPeerRateLimits"]
                owner_waves = [
                    w for o in owner_reqs for w in o["children"]
                    if w["name"] == "wave" and w.get("children")]
                if hops and owner_reqs and owner_waves:
                    stitched = (root, hops, owner_reqs, owner_waves)
                    break
            time.sleep(0.1)
        assert stitched is not None, "cross-daemon trace never stitched"
        root, hops, owner_reqs, owner_waves = stitched
        assert root["name"] == "grpc.GetRateLimits"
        # the owner-side wave is a child of the owner request span,
        # which is a child of the caller's hop span — i.e. the wave is
        # a DESCENDANT of the caller's request span, cross-daemon
        for wave in owner_waves:
            _assert_exact_partition(wave)
        ch.close()
    finally:
        c.stop()


# ---- daemon HTTP surface: /debug/traces + ?trace= ----------------------


@pytest.fixture(scope="module")
def tdaemon():
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.netutil import free_port

    d = spawn_daemon(DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address=f"127.0.0.1:{free_port()}",
        cache_size=1 << 10), engine=OracleEngine())
    d.instance.span_recorder.sample = 1.0
    yield d
    d.close()


def _get(daemon, path, timeout=10):
    url = f"http://127.0.0.1:{daemon.http_port}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as f:
        return json.loads(f.read())


def _post_check(daemon, key, timeout=60):
    body = json.dumps({"requests": [{
        "name": "traceco", "unique_key": key, "hits": 1,
        "limit": 100, "duration": 60_000}]}).encode()
    r = urllib.request.Request(
        f"http://127.0.0.1:{daemon.http_port}/v1/GetRateLimits",
        data=body, headers={"Content-Type": "application/json",
                            "traceparent": f"00-{TID}-{'cd' * 8}-01"})
    with urllib.request.urlopen(r, timeout=timeout) as f:
        return json.loads(f.read())


def test_debug_traces_endpoint(tdaemon):
    out = _post_check(tdaemon, "k_traces")
    assert out["responses"][0]["error"] == ""
    deadline = time.monotonic() + 10.0
    names = set()
    while time.monotonic() < deadline and "wave" not in names:
        body = _get(tdaemon, f"/debug/traces?trace_id={TID}")
        names = {s["name"] for s in body["spans"]}
        time.sleep(0.05)
    assert {"http.GetRateLimits", "wave"} <= names, names
    for k in ("sample", "capacity", "dropped"):
        assert k in body
    assert all(s["trace_id"] == TID for s in body["spans"])
    # limit keeps the newest N
    full = _get(tdaemon, "/debug/traces")["spans"]
    lim = _get(tdaemon, "/debug/traces?limit=2")["spans"]
    assert len(lim) == min(2, len(full)) and lim == full[-len(lim):]


def test_debug_events_trace_filter(tdaemon):
    _post_check(tdaemon, "k_evfilter")
    evs = _get(tdaemon, f"/debug/events?trace={TID}")["events"]
    assert evs and all(e.get("trace") == TID for e in evs)
    wave_evs = [e for e in evs if e["kind"].startswith("wave_")]
    assert wave_evs and all(e.get("span_id") for e in wave_evs)
    assert _get(tdaemon, "/debug/events?trace=none")["events"] == []


def test_trace_dump_written_on_close(tmp_path, monkeypatch):
    import glob
    import os

    monkeypatch.setenv("GUBER_DEBUG_DUMP_DIR", str(tmp_path))
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    inst.span_recorder.sample = 1.0
    with request_context(None, recorder=inst.span_recorder):
        with span("grpc.GetRateLimits"):
            inst.get_rate_limits([req("dump_k")], now_ms=NOW)
    inst.close()
    files = glob.glob(os.path.join(str(tmp_path), "guber_traces_*.jsonl"))
    assert len(files) == 1
    with open(files[0], encoding="utf-8") as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines[0]["kind"] == "trace_header"
    assert lines[0]["spans"] == len(lines) - 1 >= 1
    assert all("span_id" in ln for ln in lines[1:])
    # tools/trace_assemble.py stitches the spill into a waterfall
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join("tools", "trace_assemble.py"),
         files[0]],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "grpc.GetRateLimits" in out.stdout


def test_cli_debug_traces_subcommand(tdaemon):
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _post_check(tdaemon, "k_cli_traces")
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if any(s["name"] == "wave" for s in
               tdaemon.instance.span_recorder.spans(trace_id=TID)):
            break
        time.sleep(0.05)
    url = f"http://127.0.0.1:{tdaemon.http_port}"
    r = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "traces", "--url", url, "--trace-id", TID, "--json"],
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    body = json.loads(r.stdout)
    assert body["daemons"] and {s["name"] for s in body["spans"]} >= {
        "http.GetRateLimits", "wave"}
    # waterfall render: one tree, the request span on top
    r2 = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "traces", "--url", url, "--trace-id", TID, "--waterfall"],
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 0, r2.stderr
    assert f"trace {TID}" in r2.stdout
    assert "http.GetRateLimits" in r2.stdout and "#" in r2.stdout
    # events --trace: server-side filter through the CLI
    r3 = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cli", "debug",
         "events", "--url", url, "--trace", TID, "--json"],
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r3.returncode == 0, r3.stderr
    evs = json.loads(r3.stdout)["events"]
    assert evs and all(e["trace"] == TID for e in evs)


# ---- 16-thread soak: zero recorder backpressure ------------------------


@pytest.mark.slow
def test_sixteen_thread_soak_no_recorder_backpressure():
    """Armed-but-unsampled is the production default: 16 threads of
    traced traffic must leave the recorder EMPTY — no pending buildup
    (every trace commits), nothing sampled into the ring, no errors."""
    inst = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0),
                      engine=OracleEngine())
    errors = []
    try:
        inst.span_recorder.sample = 0.0
        N, T = 20, 16

        def worker(t):
            try:
                for i in range(N):
                    with request_context(None,
                                         recorder=inst.span_recorder):
                        with span("grpc.GetRateLimits"):
                            out = inst.get_rate_limits(
                                [req(f"soak{t}_{i}")], now_ms=NOW)
                    assert out[0].error == ""
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(T)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors, errors
        # late wave adds drain through tombstones within moments
        deadline = time.monotonic() + 5.0
        st = inst.span_recorder.stats()
        while time.monotonic() < deadline and st["pending"]:
            time.sleep(0.05)
            st = inst.span_recorder.stats()
        assert st["pending"] == 0, st
        assert st["spans"] == 0, st  # nothing head-sampled at rate 0
    finally:
        inst.close()

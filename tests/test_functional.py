"""Functional tests over a real in-process cluster (reference:
functional_test.go + cluster/cluster.go — SURVEY.md §4).  Real gRPC over
loopback, 4 daemons sharing the virtual CPU device mesh."""
import threading
import time

import grpc
import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.client import Client, HttpClient
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    GregorianDuration,
    RateLimitRequest,
    Status,
)

UNDER, OVER = Status.UNDER_LIMIT, Status.OVER_LIMIT


@pytest.fixture(scope="module")
def cluster():
    c = cluster_mod.start(
        4, mesh=make_mesh(n=4),
        behaviors=BehaviorConfig(
            batch_timeout_ms=30, batch_wait_ms=30,
            global_sync_wait_ms=40, global_broadcast_interval_ms=40,
            global_timeout_ms=2000))
    yield c
    c.stop()


@pytest.fixture(scope="module")
def client(cluster):
    c = Client(cluster.grpc_address(0))
    yield c
    c.close()


def req(name, key, **kw):
    d = dict(hits=1, limit=5, duration=60_000)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, **d)


class TestFunctional:
    def test_over_the_limit(self, client):
        """reference: functional_test.go › TestOverTheLimit."""
        for i, (want_status, want_rem) in enumerate(
                [(UNDER, 1), (UNDER, 0), (OVER, 0)]):
            r = client.check(req("test_over_limit", "account:1234", limit=2))
            assert r.error == ""
            assert r.status == want_status, i
            assert r.remaining == want_rem
            assert r.limit == 2

    def test_token_bucket(self, client):
        """reference: functional_test.go › TestTokenBucket."""
        t0 = time.time() * 1000
        r = client.check(req("test_token", "k1", limit=3, duration=10_000))
        assert (r.status, r.remaining) == (UNDER, 2)
        assert t0 + 9_000 <= r.reset_time <= t0 + 11_000
        r = client.check(req("test_token", "k1", hits=0, limit=3,
                             duration=10_000))
        assert (r.status, r.remaining) == (UNDER, 2)  # query doesn't mutate

    def test_token_bucket_gregorian(self, client):
        """reference: functional_test.go › TestTokenBucketGregorian."""
        r = client.check(req(
            "test_greg", "k1", limit=10,
            duration=int(GregorianDuration.HOURS),
            behavior=Behavior.DURATION_IS_GREGORIAN))
        assert (r.status, r.remaining) == (UNDER, 9)
        now_ms = time.time() * 1000
        assert r.reset_time > now_ms  # end of current hour is in the future
        assert r.reset_time <= now_ms + 3_600_000

    def test_leaky_bucket(self, client):
        """reference: functional_test.go › TestLeakyBucket."""
        n = "test_leaky"
        for want_rem in (4, 3, 2):
            r = client.check(req(n, "k1", algorithm=Algorithm.LEAKY_BUCKET,
                                 limit=5, duration=600_000))
            assert (r.status, r.remaining) == (UNDER, want_rem)
        # burst < limit
        r = client.check(req(n, "k2", algorithm=Algorithm.LEAKY_BUCKET,
                             limit=100, burst=2, duration=600_000))
        assert (r.status, r.remaining) == (UNDER, 1)

    def test_reset_remaining(self, client):
        """reference: functional_test.go › TestResetRemaining."""
        n = "test_reset"
        for _ in range(3):
            client.check(req(n, "k1", limit=3))
        r = client.check(req(n, "k1", limit=3))
        assert r.status == OVER
        r = client.check(req(n, "k1", limit=3,
                             behavior=Behavior.RESET_REMAINING))
        assert (r.status, r.remaining) == (UNDER, 2)

    def test_change_limit(self, client):
        """reference: functional_test.go › TestChangeLimit."""
        n = "test_change_limit"
        r = client.check(req(n, "k1", limit=10))
        assert r.remaining == 9
        r = client.check(req(n, "k1", limit=20))
        assert (r.limit, r.remaining) == (20, 18)
        r = client.check(req(n, "k1", limit=5))
        assert (r.limit, r.remaining) == (5, 2)

    def test_drain_over_limit(self, client):
        """reference: functional_test.go › TestDrainOverLimit
        (version-dependent flag, implemented)."""
        n = "test_drain"
        r = client.check(req(n, "k1", limit=5, hits=3,
                             behavior=Behavior.DRAIN_OVER_LIMIT))
        assert (r.status, r.remaining) == (UNDER, 2)
        r = client.check(req(n, "k1", limit=5, hits=3,
                             behavior=Behavior.DRAIN_OVER_LIMIT))
        assert (r.status, r.remaining) == (OVER, 0)  # drained

    def test_requests_forwarded_to_owner(self, cluster, client):
        """Non-owned keys must be forwarded: state lives on exactly one
        daemon (gubernator.go › GetRateLimits fan-out)."""
        # find a key daemon 0 does NOT own
        inst0 = cluster.instance_at(0)
        key = None
        for i in range(100):
            k = f"fwd_key_{i}"
            owner = inst0.owner_of(f"test_forward_{k}")
            if owner is not None and not inst0.is_self(owner):
                key = k
                break
        assert key is not None
        r = client.check(req("test_forward", key, limit=7))
        assert (r.status, r.remaining) == (UNDER, 6)
        # asking the owner daemon directly must see the same counter
        owner_d = cluster.owner_daemon_of(f"test_forward_{key}")
        with Client(owner_d.advertise_address) as oc:
            r = oc.check(req("test_forward", key, limit=7))
            assert (r.status, r.remaining) == (UNDER, 5)

    def test_no_batching(self, client):
        r = client.check(req("test_nobatch", "k1", limit=3,
                             behavior=Behavior.NO_BATCHING))
        assert (r.status, r.remaining) == (UNDER, 2)

    def test_global_rate_limits(self, cluster, client):
        """reference: functional_test.go › TestGlobalRateLimits — hits on
        a non-owner converge to the owner and broadcast back.

        Convergence is polled by ATTEMPT COUNT, not wall-clock: each
        attempt is a real RPC round trip plus the async flush it gives
        the daemons a chance to run, so on a contended host (the 1-core
        CI box under a concurrent fuzz run — the round-3 flake) the
        budget stretches with the slowdown instead of expiring while
        the daemons are starved of cycles.  100 attempts ≈ 5 s idle."""
        name, key = "test_global", "account:77"
        r = client.check(req(name, key, limit=100, hits=2,
                             behavior=Behavior.GLOBAL))
        assert r.status == UNDER
        owner_d = cluster.owner_daemon_of(f"{name}_{key}")

        def owner_remaining():
            with Client(owner_d.advertise_address) as oc:
                rr = oc.check(req(name, key, limit=100, hits=0,
                                  behavior=Behavior.GLOBAL))
                return rr.remaining

        # owner applies the async-reconciled hits within the sync window
        for _ in range(100):
            if owner_remaining() == 98:
                break
            time.sleep(0.05)
        assert owner_remaining() == 98
        # and every replica converges via the broadcast
        ok = False
        for _ in range(100):
            ok = True
            for i in range(4):
                with Client(cluster.grpc_address(i)) as pc:
                    rr = pc.check(req(name, key, limit=100, hits=0,
                                      behavior=Behavior.GLOBAL))
                    if rr.remaining != 98:
                        ok = False
            if ok:
                break
            time.sleep(0.05)
        assert ok, "replicas did not converge to owner state"

    def test_health_check(self, cluster, client):
        """reference: functional_test.go › TestHealthCheck.

        A prior test's async flush can time out under CI load, which
        legitimately marks the daemon unhealthy for the 60 s error TTL
        — poll past it rather than flake."""
        import time as _t

        deadline = _t.time() + 75
        h = client.health_check()
        while h.status != "healthy" and _t.time() < deadline:
            _t.sleep(1.0)
            h = client.health_check()
        assert h.status == "healthy", h
        assert h.peer_count == 4

    def test_multiple_async(self, client):
        """reference: functional_test.go › TestMultipleAsync — concurrent
        batches don't lose counts."""
        n = "test_async"
        errs = []

        def worker(w):
            try:
                resps = client.get_rate_limits(
                    [req(n, f"k{w}_{i}", limit=9) for i in range(20)])
                assert all(r.error == "" and r.status == UNDER
                           for r in resps)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        # every key must have exactly one hit recorded
        resps = client.get_rate_limits(
            [req(n, f"k{w}_{i}", hits=0, limit=9)
             for w in range(8) for i in range(20)])
        assert all(r.remaining == 8 for r in resps)

    def test_batch_too_large(self, client):
        with pytest.raises(grpc.RpcError) as ei:
            client.get_rate_limits(
                [req("test_big", f"k{i}") for i in range(1001)])
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_empty_fields_error(self, client):
        r = client.check(RateLimitRequest(name="x", unique_key="",
                                          limit=1, duration=1000))
        assert "unique_key" in r.error
        r = client.check(RateLimitRequest(name="", unique_key="x",
                                          limit=1, duration=1000))
        assert "name" in r.error

    def test_http_gateway(self, cluster):
        """grpc-gateway mirror: JSON in/out + health + metrics."""
        hc = HttpClient(cluster.http_address(0))
        r = hc.get_rate_limits([req("test_http", "k1", limit=4)])[0]
        assert (r.status, r.remaining) == (0, 3)
        h = hc.health_check()
        assert h.status == "healthy" and h.peer_count == 4
        import urllib.request

        with urllib.request.urlopen(
                cluster.http_address(0) + "/metrics", timeout=10) as f:
            text = f.read().decode()
        assert "gubernator_getratelimit" in text
        assert "gubernator_cache_size" in text

    def test_metadata_round_trip(self, client):
        r = client.check(req("test_meta", "k1", limit=3,
                             metadata={"client": "abc"}))
        assert r.error == ""

"""tools/hostpath_prof.py smoke (tier-1, ISSUE 2 satellite): the
reproducible §4.2 host-glue profiler runs end-to-end and reports all
four buckets, so a perf round can always regenerate the breakdown."""
import json
import os
import sys

import pytest

pytest.importorskip("gubernator_tpu.ops.native")

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


def test_hostpath_prof_reports_all_buckets(capsys):
    import hostpath_prof

    rc = hostpath_prof.main(["--reqs", "64", "--reps", "3",
                             "--cache-size", "4096"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    buckets = out["buckets_ms_per_call"]
    # §4.2's four decomposition buckets, all present
    for b in ("device_step", "parse_pack", "dispatch_future",
              "response_build"):
        assert b in buckets, buckets
    assert out["total_ms_per_call"] > 0
    assert out["host_glue_ms_per_call"] >= 0
    assert out["reps"] == 3
    # the instrumented run actually exercised the serving path
    assert out["buffer_pool"]["hits"] + out["buffer_pool"]["misses"] > 0
